"""skelly-scope: span tracing, compile events, cost baselines, convergence
history (docs/observability.md).

Covers every leg of the telemetry subsystem: span nesting/attribution in
the tracer, compile events firing exactly once per compiled program
(cross-checked against `testing.trace_counting_jit`), the cost-baseline
drift gate's flag/pass/suppress/drift ladder (synthetic programs + the real
CLI on the cheapest registered program), and the GMRES convergence ring
buffer against the solver's own debug-print residuals. Multi-device
fixture compiles stay out of this module (the cost CLI test restricts to
``gmres_f32``) to protect the not-slow tier's 870 s budget.
"""

import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from skellysim_tpu.obs import tracer as obs_tracer
from skellysim_tpu.obs.compile_log import observed_jit
from skellysim_tpu.obs.tracer import TELEMETRY_VERSION, Tracer


# ------------------------------------------------------------------ tracer

def test_span_nesting_and_attribution():
    tr = Tracer()  # in-memory
    with obs_tracer.use(tr):
        with obs_tracer.span("outer", kind="test"):
            with obs_tracer.span("inner") as sp:
                sp.note(iters=3)
            with obs_tracer.span("inner"):
                pass
    evs = tr.events
    assert evs[0]["ev"] == "telemetry"
    assert evs[0]["version"] == TELEMETRY_VERSION
    spans = [e for e in evs if e["ev"] == "span"]
    # children close before their parent; paths carry the open stack
    assert [s["path"] for s in spans] == ["outer/inner", "outer/inner",
                                         "outer"]
    assert spans[0]["iters"] == 3
    assert spans[2]["kind"] == "test"
    assert all(s["dur_s"] >= 0.0 and "pid" in s and "host" in s
               for s in spans)
    # the parent's duration covers its children
    assert spans[2]["dur_s"] >= spans[0]["dur_s"] + spans[1]["dur_s"]


def test_span_sync_blocks_on_device_work():
    tr = Tracer()
    with obs_tracer.use(tr):
        with obs_tracer.span("work") as sp:
            sp.sync(jnp.ones((8, 8)) @ jnp.ones((8, 8)))
    (span,) = [e for e in tr.events if e["ev"] == "span"]
    assert span["name"] == "work"


def test_span_and_emit_are_noops_without_tracer():
    assert obs_tracer.active() is None
    with obs_tracer.span("nobody-listening") as sp:
        sp.note(x=1)
        sp.sync(jnp.zeros(3))
    obs_tracer.emit("lane", action="admit")  # must not raise


def test_tracer_jsonl_roundtrip(tmp_path):
    path = str(tmp_path / "trace.jsonl")
    tr = Tracer(path)
    with tr.span("a"):
        tr.emit("custom", value=7)
    tr.close()
    recs = [json.loads(ln) for ln in open(path)]
    assert [r["ev"] for r in recs] == ["telemetry", "custom", "span"]
    assert recs[1]["value"] == 7


# ----------------------------------------------------------- compile events

def test_compile_events_fire_exactly_once_per_program():
    """One compile event per (program x signature) — cross-checked against
    trace_counting_jit semantics via the shared trace counter."""
    from skellysim_tpu.testing import trace_counting_jit

    def f(x):
        return (x * 2.0).sum()

    obs = observed_jit(f, name="toy")
    ref = trace_counting_jit(f)
    tr = Tracer()
    with obs_tracer.use(tr):
        x = jnp.ones(8)
        obs(x), ref(x)
        obs(x + 1.0), ref(x + 1.0)      # same signature: no event
        obs(jnp.ones(16)), ref(jnp.ones(16))  # new shape: one more event
    compiles = [e for e in tr.events if e["ev"] == "compile"]
    assert len(compiles) == 2
    assert obs.trace_count == ref.trace_count == 2
    assert [c["name"] for c in compiles] == ["toy", "toy"]
    assert compiles[0]["arg_sig"].startswith("f64[8]")
    assert compiles[1]["arg_sig"].startswith("f64[16]")
    assert all(c["wall_s"] >= c["trace_s"] >= 0.0 for c in compiles)


def test_compile_event_skipped_when_warm():
    """A tracer installed AFTER the program compiled sees no event — only
    genuine compiles land in the timeline."""
    g = observed_jit(lambda x: x + 1.0, name="warm")
    g(jnp.ones(4))
    tr = Tracer()
    with obs_tracer.use(tr):
        g(jnp.ones(4))
    assert [e for e in tr.events if e["ev"] == "compile"] == []


def test_observed_jit_trace_passthrough_and_donation_field():
    """`built_from` consumes ObservedJit directly (the audit/cost seam) and
    the compile event carries the donated argument positions."""
    from skellysim_tpu.audit.registry import built_from

    h = observed_jit(lambda x: x * 3.0, name="donating", donate_argnums=(0,))
    built = built_from(h, jnp.ones(4))
    assert built.lowered is not None
    assert "stablehlo" in built.lowered_text or "func.func" in built.lowered_text
    tr = Tracer()
    with obs_tracer.use(tr):
        h(jnp.ones(8))
    (ev,) = [e for e in tr.events if e["ev"] == "compile"]
    assert ev["donated"] == [0]


# ------------------------------------------------------------ cost baselines

def _toy_program(name="toy_prog", scale=1.0):
    from skellysim_tpu.audit.registry import AuditProgram, built_from

    def build():
        a = jnp.ones((32, 32)) * scale
        return built_from(jax.jit(lambda x: (x @ x).sum()), a)

    return AuditProgram(name=name, layer="solver", summary="toy", build=build)


def test_cost_uncovered_then_update_then_pass(tmp_path):
    from skellysim_tpu.obs import cost

    prog = _toy_program()
    bdir = str(tmp_path)
    rows, findings = cost.audit_costs([prog], baseline_dir=bdir)
    assert any("no cost baseline" in f.message for f in findings)
    assert rows[0]["flops"] > 0 and rows[0]["peak_bytes"] > 0

    rows, findings = cost.audit_costs([prog], baseline_dir=bdir, update=True)
    assert findings == []
    rows, findings = cost.audit_costs([prog], baseline_dir=bdir)
    assert findings == []  # measured == baseline: deterministic static analysis


def test_cost_drift_flagged_and_suppressible(tmp_path):
    from skellysim_tpu.config import toml_io
    from skellysim_tpu.obs import cost

    prog = _toy_program()
    bdir = str(tmp_path)
    cost.audit_costs([prog], baseline_dir=bdir, update=True)
    path = cost.baseline_path(prog.name, bdir)
    data = toml_io.load(path)
    data["cost"]["flops"] = data["cost"]["flops"] * 2.0  # fake a regression
    toml_io.dump(data, path)
    _, findings = cost.audit_costs([prog], baseline_dir=bdir)
    assert any("flops drifted" in f.message and "improvement" in f.message
               for f in findings)

    # suppression with a reason absorbs it; an unused one is itself a finding
    data["suppress"] = [{"check": "cost-baseline", "match": "flops drifted",
                         "reason": "testing the suppress path"}]
    toml_io.dump(data, path)
    _, findings = cost.audit_costs([prog], baseline_dir=bdir)
    assert findings == []
    data["cost"]["flops"] = data["cost"]["flops"] / 2.0  # back to truth
    toml_io.dump(data, path)
    _, findings = cost.audit_costs([prog], baseline_dir=bdir)
    assert any("unused suppression" in f.message for f in findings)


def test_cost_suppress_requires_reason_and_match(tmp_path):
    from skellysim_tpu.config import toml_io
    from skellysim_tpu.obs import cost

    prog = _toy_program()
    bdir = str(tmp_path)
    cost.audit_costs([prog], baseline_dir=bdir, update=True)
    path = cost.baseline_path(prog.name, bdir)
    data = toml_io.load(path)
    data["suppress"] = [{"check": "cost-baseline", "match": "flops"}]
    toml_io.dump(data, path)
    _, findings = cost.audit_costs([prog], baseline_dir=bdir)
    assert any("missing its reason" in f.message for f in findings)


def test_cost_stale_baseline_and_tol_pct(tmp_path):
    from skellysim_tpu.config import toml_io
    from skellysim_tpu.obs import cost

    prog = _toy_program()
    bdir = str(tmp_path)
    cost.audit_costs([prog], baseline_dir=bdir, update=True)
    # a generous tol_pct absorbs a small nudge (and --update preserves it)
    path = cost.baseline_path(prog.name, bdir)
    data = toml_io.load(path)
    data["cost"]["tol_pct"] = 90.0
    data["cost"]["flops"] = data["cost"]["flops"] * 1.5
    toml_io.dump(data, path)
    _, findings = cost.audit_costs([prog], baseline_dir=bdir)
    assert findings == []
    cost.audit_costs([prog], baseline_dir=bdir, update=True)
    assert toml_io.load(path)["cost"]["tol_pct"] == 90.0
    # a baseline whose program vanished is a finding
    _, findings = cost.audit_costs([_toy_program(name="other")],
                                   baseline_dir=bdir)
    assert any("stale baseline" in f.message for f in findings)
    assert any("no cost baseline" in f.message for f in findings)


def test_cost_cli_exit_codes(tmp_path):
    """`obs cost --check` exits 1 on drift/uncovered, 0 once baselined —
    on the real registry restricted to its cheapest program (gmres_f32;
    the multi-device programs stay in the CI gate, not the test tier)."""
    from skellysim_tpu.obs.cli import main

    bdir = str(tmp_path)
    assert main(["cost", "--check", "--program", "gmres_f32",
                 "--baseline-dir", bdir]) == 1  # uncovered
    # findings exit 1 with or without --check (mirrors lint/audit)
    assert main(["cost", "--program", "gmres_f32",
                 "--baseline-dir", bdir]) == 1
    assert main(["cost", "--update", "--program", "gmres_f32",
                 "--baseline-dir", bdir]) == 0
    assert main(["cost", "--check", "--program", "gmres_f32",
                 "--baseline-dir", bdir]) == 0
    assert main(["cost", "--check", "--update"]) == 2  # usage error
    assert main(["cost", "--check", "--program", "nope",
                 "--baseline-dir", bdir]) == 2
    # against the REAL baseline dir, a single-program run must not read
    # the other programs' baselines as stale (the --program workflow)
    assert main(["cost", "--check", "--program", "gmres_f32"]) == 0


def test_cost_stale_scan_uses_full_registry_names(tmp_path):
    from skellysim_tpu.obs import cost

    a, b = _toy_program(name="prog_a"), _toy_program(name="prog_b")
    bdir = str(tmp_path)
    cost.audit_costs([a, b], baseline_dir=bdir, update=True)
    # auditing only prog_a with the full name set: prog_b's baseline is fine
    _, findings = cost.audit_costs([a], baseline_dir=bdir,
                                   registry_names={"prog_a", "prog_b"})
    assert findings == []
    # without the full set (a caller that filtered and forgot): stale
    _, findings = cost.audit_costs([a], baseline_dir=bdir)
    assert any("stale baseline" in f.message for f in findings)


def test_every_registered_program_has_a_checked_in_baseline():
    """Acceptance pin: the registry and obs/baselines/ agree exactly (the
    full drift check runs in CI; here only the cheap file<->name match)."""
    import os

    from skellysim_tpu.audit.programs import all_programs
    from skellysim_tpu.obs.cost import BASELINE_DIR

    names = {p.name for p in all_programs()}
    files = {os.path.splitext(f)[0] for f in os.listdir(BASELINE_DIR)
             if f.endswith(".toml")}
    assert names == files


# ------------------------------------------------- gmres convergence history

def _dense_problem(n=80, seed=3, dtype=jnp.float64):
    rng = np.random.default_rng(seed)
    A = jnp.asarray(np.eye(n) + 0.3 * rng.standard_normal((n, n)) / np.sqrt(n),
                    dtype=dtype)
    b = jnp.asarray(rng.standard_normal(n), dtype=dtype)
    return A, b


def test_gmres_history_matches_debug_print(capsys):
    """The device-side ring buffer records the SAME per-restart residuals
    the solver's debug path prints — without any host callback in the
    compiled program (the debug path adds one; history must not)."""
    from skellysim_tpu.solver.gmres import gmres, history_rows

    A, b = _dense_problem()
    r = gmres(lambda x: A @ x, b, tol=1e-12, restart=5, maxiter=200,
              history=16, debug=True)
    jax.effects_barrier()
    printed = []
    for ln in capsys.readouterr().out.splitlines():
        if "gmres restart" in ln:
            printed.append((int(ln.split("iters=")[1].split(" ")[0]),
                            float(ln.split("implicit=")[1].split(" ")[0]),
                            float(ln.split("explicit=")[1])))
    rows = history_rows(r.history, r.cycles)
    assert len(rows) == len(printed) == int(r.cycles) >= 3
    for (it_h, imp_h, exp_h), (it_p, imp_p, exp_p) in zip(rows, printed):
        assert it_h == it_p
        assert imp_h == pytest.approx(imp_p, rel=2e-3)  # print is %.3e
        assert exp_h == pytest.approx(exp_p, rel=2e-3)
    assert rows[-1][2] == float(r.residual_true)


def test_gmres_history_ring_wraps_chronologically():
    from skellysim_tpu.solver.gmres import gmres, history_rows

    A, b = _dense_problem()
    full = gmres(lambda x: A @ x, b, tol=1e-12, restart=5, maxiter=200,
                 history=32)
    wrapped = gmres(lambda x: A @ x, b, tol=1e-12, restart=5, maxiter=200,
                    history=3)
    all_rows = history_rows(full.history, full.cycles)
    last3 = history_rows(wrapped.history, wrapped.cycles)
    assert int(full.cycles) > 3  # the wrap actually happened
    assert len(last3) == 3
    assert last3 == all_rows[-3:]  # ring holds the LAST cycles, oldest first
    # disabled history costs nothing and changes nothing
    off = gmres(lambda x: A @ x, b, tol=1e-12, restart=5, maxiter=200)
    assert off.history is None
    np.testing.assert_array_equal(np.asarray(off.x), np.asarray(full.x))


def test_gmres_ir_history_one_row_per_sweep():
    from skellysim_tpu.solver.gmres import gmres_ir, history_rows

    A, b = _dense_problem()
    r = gmres_ir(lambda x: A @ x, lambda x: A @ x, b, tol=1e-12,
                 inner_tol=1e-4, restart=30, maxiter=200, history=8)
    rows = history_rows(r.history, r.cycles)
    assert len(rows) == int(r.refines) == int(r.cycles) >= 2
    assert rows[-1][2] == float(r.residual_true)
    exps = [row[2] for row in rows]
    assert exps == sorted(exps, reverse=True)  # sweeps contract the residual


def test_history_rows_handles_empty_and_none():
    from skellysim_tpu.solver.gmres import history_rows

    assert history_rows(None, 5) == []
    assert history_rows(np.zeros((4, 3)), 0) == []
    assert history_rows(np.zeros((0, 3)), 3) == []


def test_vmapped_gmres_history_is_per_member():
    """The ring buffer is an ordinary carry: vmap gives each member its own
    buffer (the ensemble runner's per-lane convergence history)."""
    from skellysim_tpu.solver.gmres import gmres, history_rows

    A, b = _dense_problem()
    bb = jnp.stack([b, 2.0 * b])
    vr = jax.vmap(lambda bi: gmres(lambda x: A @ x, bi, tol=1e-12,
                                   restart=5, maxiter=200, history=8))(bb)
    assert vr.history.shape[0] == 2
    r0 = history_rows(vr.history[0], vr.cycles[0])
    r1 = history_rows(vr.history[1], vr.cycles[1])
    # scaled RHS: same relative trajectory, per-member buffers decode alone
    assert len(r0) == len(r1) == int(vr.cycles[0])
    assert r0[-1][2] == pytest.approx(float(vr.residual_true[0]))


# ---------------------------------------------------- run-loop + ensemble

def test_run_metrics_and_trace_render_through_summarize(tmp_path):
    """Acceptance criterion: System.run(metrics_path, trace_path) -> `obs
    summarize` renders per-span timings, compile events, and convergence
    stats from the pair."""
    from skellysim_tpu.audit import fixtures
    from skellysim_tpu.obs.summarize import summarize_files
    from skellysim_tpu.system.system import METRICS_FIELDS

    system = fixtures.make_system()
    state = fixtures.free_state(system)
    m = str(tmp_path / "metrics.jsonl")
    t = str(tmp_path / "trace.jsonl")
    system.run(state, max_steps=2, metrics_path=m, trace_path=t)

    recs = [json.loads(ln) for ln in open(m)]
    assert len(recs) == 2
    for rec in recs:
        assert set(rec) == set(METRICS_FIELDS)
        assert rec["gmres_cycles"] >= 1
        assert rec["wall_ms"] == pytest.approx(rec["wall_s"] * 1e3, rel=0.1)
        hist = rec["gmres_history"]
        assert len(hist) == rec["gmres_cycles"]
        # last ring row's explicit residual is the step's residual_true
        assert hist[-1][2] == pytest.approx(rec["residual_true"])
        assert hist[-1][0] == rec["iters"]

    evs = [json.loads(ln) for ln in open(t)]
    kinds = [e["ev"] for e in evs]
    assert kinds[0] == "telemetry"
    assert "compile" in kinds and "span" in kinds
    (compile_ev,) = [e for e in evs if e["ev"] == "compile"]
    assert compile_ev["name"] == "system.solve"  # compiled exactly once
    step_spans = [e for e in evs if e["ev"] == "span"
                  and e["name"] == "step"]
    assert len(step_spans) == 2
    assert all(s["path"] == "run/step" for s in step_spans)

    report = summarize_files([m, t])
    for section in ("== spans ==", "== compile events ==",
                    "== solver convergence =="):
        assert section in report
    assert "run/step" in report and "system.solve" in report


@pytest.mark.slow
def test_scheduler_lane_events_and_no_backfill_retrace(tmp_path):
    """Lane admit/backfill/retire events flow through the tracer, occupancy
    renders in summarize, and the telemetry does not break the
    backfill-never-retraces invariant (trace_counting_jit cross-check).

    Slow-marked (a 4-member batched-step compile) to keep the not-slow
    tier inside the driver's 870 s budget; the full tier runs it."""
    from skellysim_tpu.audit import fixtures
    from skellysim_tpu.ensemble import (EnsembleRunner, EnsembleScheduler,
                                        MemberSpec)
    from skellysim_tpu.io.ensemble_io import ENSEMBLE_STEP_FIELDS
    from skellysim_tpu.obs.summarize import summarize_files
    from skellysim_tpu.system import BackgroundFlow
    from skellysim_tpu.testing import trace_counting_jit

    system = fixtures.make_system()
    states = [system.make_state(
        fibers=fixtures.make_fibers(n_fibers=2, n_nodes=8, seed=i),
        background=BackgroundFlow.make(uniform=(1.0, 0.0, 0.0),
                                       dtype=jnp.float64))
        for i in range(4)]
    members = [MemberSpec(member_id=f"m{i}", state=s, t_final=2e-3)
               for i, s in enumerate(states)]
    runner = EnsembleRunner(system)
    counting = trace_counting_jit(runner.step_impl)

    metrics_records = []
    t = str(tmp_path / "trace.jsonl")
    tr = Tracer(t)
    with obs_tracer.use(tr):
        sched = EnsembleScheduler(runner, members, 2,
                                  metrics=metrics_records.append,
                                  step_fn=counting)
        retired = sched.run()
    tr.close()
    assert sorted(retired) == ["m0", "m1", "m2", "m3"]
    # lane events: 2 admits (initial seats), 2 backfills, 4 retires — and
    # backfill swapped member leaves without a retrace
    evs = [json.loads(ln) for ln in open(t)]
    lanes = [e for e in evs if e["ev"] == "lane"]
    actions = [e["action"] for e in lanes]
    assert actions.count("admit") == 2
    assert actions.count("backfill") == 2
    assert actions.count("retire") == 4
    assert counting.trace_count == 1
    steps = [r for r in metrics_records if r["event"] == "step"]
    assert steps and all(set(r) == set(ENSEMBLE_STEP_FIELDS) for r in steps)
    assert all(len(r["gmres_history"]) == r["gmres_cycles"] for r in steps)

    report = summarize_files([t])
    assert "== ensemble lanes ==" in report
    assert "mean occupancy" in report
    assert "admit=2" in report and "backfill=2" in report


# ------------------------------------------------------------- bench format

def test_bench_telemetry_version_pinned():
    """bench.py's jax-free parent pins its own TELEMETRY_VERSION literal;
    it must track obs.tracer's (the one-format contract)."""
    import importlib.util
    import os

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "bench_for_version_pin", os.path.join(repo, "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    assert bench.TELEMETRY_VERSION == TELEMETRY_VERSION


def test_summarize_tolerates_mixed_and_garbage_lines(tmp_path):
    from skellysim_tpu.obs.summarize import summarize_files

    p = str(tmp_path / "mixed.jsonl")
    with open(p, "w") as fh:
        fh.write("not json at all\n")
        fh.write(json.dumps({"resume": True, "t": 0.5}) + "\n")
        fh.write(json.dumps({"ev": "span", "name": "a", "path": "a",
                             "dur_s": 0.5}) + "\n")
        fh.write(json.dumps({"step": 0, "iters": 4, "accepted": True,
                             "residual_true": 1e-11}) + "\n")
    report = summarize_files([p])
    assert "== spans ==" in report
    assert "trial steps: 1" in report
    assert "resume markers: 1" in report
    assert "1 unparseable line(s) skipped" in report


def test_summarize_dedupes_shared_round_wall(tmp_path):
    """Ensemble step records share one batched round's wall across lanes;
    the wall total must count each round once, not lanes x wall."""
    from skellysim_tpu.obs.summarize import summarize_files

    p = str(tmp_path / "ens.jsonl")
    with open(p, "w") as fh:
        for rnd in range(2):
            for lane in range(4):
                fh.write(json.dumps({
                    "event": "step", "member": f"m{lane}", "lane": lane,
                    "round": rnd, "step": rnd, "iters": 3, "accepted": True,
                    "wall_ms": 10.0}) + "\n")
    report = summarize_files([p])
    # 2 rounds x 10 ms = 0.020 s — NOT 8 records x 10 ms = 0.080 s
    assert "batched-round wall: total 0.020s" in report
    # two runs' files summarized together: per-run round ids both start at
    # 0, so the dedupe must key per stream — totals ADD across files
    import shutil

    p2 = str(tmp_path / "ens2.jsonl")
    shutil.copy(p, p2)
    assert "batched-round wall: total 0.040s" in summarize_files([p, p2])


# ----------------------------------------------- skelly-pulse: profile dumps

import os

PROFILE_FIXTURE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "golden", "profile_fixture")


def test_profile_fixture_phase_attribution():
    """Phase-table parsing on the checked-in miniature trace-event fixture
    (a 2-virtual-device shard_map program using the real phase vocabulary;
    no TPU, no profiling at test time)."""
    from skellysim_tpu.obs import profile as profile_mod

    trace = profile_mod.load_device_trace(PROFILE_FIXTURE)
    assert trace.total_us > 0
    phases = {g["key"]: g for g in trace.by_phase()}
    for key in ("prep", "gmres/arnoldi", "gmres/psum-dots", "advance"):
        assert key in phases, sorted(phases)
    # the fixture's psum lands as an all_reduce, split out by kind under
    # the audit contract's spelling
    assert "all_reduce" in phases["gmres/psum-dots"]["collectives"]
    kinds = {g["key"] for g in trace.by_collective()}
    assert "all_reduce" in kinds and "(computation)" in kinds
    # >= 90% attributed, unattributed reported (not hidden)
    assert trace.attributed_frac >= 0.9
    assert "(unattributed)" in phases or trace.attributed_frac == 1.0
    # shares are a partition of the total
    assert sum(g["share"] for g in trace.by_phase()) == pytest.approx(1.0)


def test_profile_render_and_json():
    from skellysim_tpu.obs import profile as profile_mod

    trace = profile_mod.load_device_trace(PROFILE_FIXTURE)
    table = profile_mod.render_table(trace, by="phase")
    assert "attributed to named phases" in table
    assert "gmres/psum-dots" in table
    doc = profile_mod.profile_json(trace)
    assert doc["total_us"] > 0
    assert {"by_phase", "by_collective", "by_op"} <= set(doc)


def test_profile_cli(tmp_path, capsys):
    from skellysim_tpu.obs.cli import main

    assert main(["profile", PROFILE_FIXTURE]) == 0
    assert "prep" in capsys.readouterr().out
    assert main(["profile", PROFILE_FIXTURE, "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["attributed_frac"] >= 0.9
    assert main(["profile", PROFILE_FIXTURE, "--by", "collective"]) == 0
    capsys.readouterr()
    assert main(["profile", str(tmp_path / "nope")]) == 2


def test_phase_of_and_collective_kind():
    from skellysim_tpu.obs.profile import collective_kind, phase_of

    assert phase_of("jit(step)/jit(main)/prep/dot_general") == "prep"
    assert phase_of("jit(step)/gmres/jit(gmres)/arnoldi/precond/mul") \
        == "gmres/arnoldi/precond"
    # immediate repeats dedupe (scopes re-entered per ring hop)
    assert phase_of("a/ring-step/ring-step/b") == "ring-step"
    assert phase_of("jit(f)/jit(main)/transpose/mul") is None
    assert collective_kind("all-reduce.17") == "all_reduce"
    assert collective_kind("all-gather") == "all_gather"
    assert collective_kind("collective-permute.3") == "collective_permute"
    # the TPU lowering's async pairs + fused thunks classify too
    assert collective_kind("all-reduce-start.5") == "all_reduce"
    assert collective_kind("all-gather-done.2") == "all_gather"
    assert collective_kind("all-reduce-fusion") == "all_reduce"
    assert collective_kind("dot.3") is None
    assert collective_kind("reduce-scatter-start") == "reduce_scatter"


def test_device_phase_events_and_emit(tmp_path):
    """`device_phase` telemetry records from a dump, emitted into a tracer
    (the --profile auto-append workflow) and rendered by summarize."""
    from skellysim_tpu.obs import profile as profile_mod
    from skellysim_tpu.obs.summarize import summarize_files

    recs = profile_mod.device_phase_events(PROFILE_FIXTURE)
    assert any(r["phase"] == "gmres/psum-dots" for r in recs)
    assert all(r["dur_s"] >= 0.0 and "share" in r for r in recs)

    tr = Tracer(str(tmp_path / "t.jsonl"))
    n = profile_mod.emit_device_phases(PROFILE_FIXTURE, tr)
    tr.close()
    assert n == len(recs) > 0
    report = summarize_files([str(tmp_path / "t.jsonl")])
    assert "== device time by phase ==" in report
    assert "gmres/psum-dots" in report
    # a broken dump emits a device_phase_error event, never raises
    tr2 = Tracer()
    assert profile_mod.emit_device_phases(str(tmp_path), tr2) == 0
    assert [e["ev"] for e in tr2.events[1:]] == ["device_phase_error"]


@pytest.mark.slow
def test_d2_spmd_profile_attribution(tmp_path):
    """Acceptance pin (ISSUE 14): `obs profile` on a CPU-run profile dir
    of the d2 SPMD coupled solve attributes >= 90% of device op time to a
    named phase, with collective kinds split out matching the audit
    contract inventory. Slow-marked: one d2 mesh compile."""
    import numpy as np

    from skellysim_tpu.audit import fixtures
    from skellysim_tpu.obs import profile as profile_mod
    from skellysim_tpu.parallel.mesh import make_mesh

    system = fixtures.make_system(shell=True)
    state = fixtures.coupled_state(system)
    mesh = make_mesh(2)
    _, sol, _ = system.step_spmd(state, mesh, donate=False)
    np.asarray(sol)   # compile + drain outside the capture window
    prof_dir = str(tmp_path / "prof_d2")
    with profile_mod.profile_session(prof_dir):
        _, sol, _ = system.step_spmd(state, mesh, donate=False)
        np.asarray(sol)
    trace = profile_mod.load_device_trace(prof_dir)
    assert trace.attributed_frac >= 0.9, profile_mod.render_table(trace)
    kinds = {g["key"] for g in trace.by_collective()}
    # the audit contract inventory of the SPMD step: psum'd dots/flows,
    # the density all-gather, the ppermute source rings
    assert {"all_reduce", "all_gather", "collective_permute"} <= kinds
    phases = {g["key"] for g in trace.by_phase()}
    assert {"prep", "gmres/arnoldi", "advance"} <= phases


# ------------------------------------------------ skelly-pulse: timeline

def test_timeline_roundtrip(tmp_path):
    """Emit spans -> perfetto JSON -> re-parse -> the same span tree
    (names + nesting by slice containment), with compile instants and
    process/thread metadata."""
    from skellysim_tpu.obs.timeline import HOST_PID, write_timeline

    path = str(tmp_path / "trace.jsonl")
    tr = Tracer(path)
    with obs_tracer.use(tr):
        with obs_tracer.span("run"):
            with obs_tracer.span("step", step=0):
                with obs_tracer.span("write_frame"):
                    pass
            with obs_tracer.span("step", step=1):
                pass
        tr.emit("compile", name="system.solve", wall_s=1.0, trace_s=0.5,
                traces=1)
        tr.emit("lane", action="admit", lane=0, member="m0")
    tr.close()

    out = str(tmp_path / "tl.json")
    counts = write_timeline([path], out)
    assert counts["host_slices"] == 4
    assert counts["instants"] == 2  # compile + lane

    doc = json.load(open(out))
    evs = doc["traceEvents"]
    procs = [e for e in evs if e.get("ph") == "M"
             and e.get("name") == "process_name"]
    assert any(e["args"]["name"] == "host telemetry" for e in procs)
    slices = sorted((e for e in evs if e.get("ph") == "X"
                     and e["pid"] == HOST_PID), key=lambda e: e["ts"])
    assert [s["name"] for s in slices] == ["run", "step", "write_frame",
                                           "step"]

    def contains(a, b):   # slice a covers slice b (small float slack)
        return (a["ts"] <= b["ts"] + 1e-6
                and a["ts"] + a["dur"] >= b["ts"] + b["dur"] - 1e-6)

    run, s0, wf, s1 = slices
    assert contains(run, s0) and contains(run, s1) and contains(s0, wf)
    assert not contains(s0, s1) and not contains(s1, s0)
    assert s1["args"]["step"] == 1
    (compile_i,) = [e for e in evs if e.get("ph") == "i"
                    and e["name"].startswith("compile ")]
    assert compile_i["args"]["wall_s"] == 1.0
    assert any(e.get("ph") == "i" and e["name"] == "lane:admit"
               for e in evs)


def test_timeline_with_device_track(tmp_path):
    from skellysim_tpu.obs.timeline import DEVICE_PID, write_timeline

    path = str(tmp_path / "trace.jsonl")
    tr = Tracer(path)
    with tr.span("step"):
        pass
    tr.close()
    out = str(tmp_path / "tl.json")
    counts = write_timeline([path], out, profile_dir=PROFILE_FIXTURE)
    assert counts["device_slices"] > 0
    doc = json.load(open(out))
    evs = doc["traceEvents"]
    dev_threads = {e["args"]["name"] for e in evs
                   if e.get("ph") == "M" and e.get("name") == "thread_name"
                   and e.get("pid") == DEVICE_PID}
    # multi-device-thread profiles suffix "[dev k]" per source thread
    # (per-tid slices must nest — overlapping same-phase slices from two
    # devices on one tid would render wrong in Perfetto)
    assert any(n == "gmres/psum-dots" or n.startswith("gmres/psum-dots [")
               for n in dev_threads), dev_threads
    # host and device tracks are separate processes
    procs = {e["args"]["name"] for e in evs
             if e.get("ph") == "M" and e.get("name") == "process_name"}
    assert {"host telemetry", "device (profiler)"} <= procs


def test_timeline_cli(tmp_path, capsys):
    from skellysim_tpu.obs.cli import main

    path = str(tmp_path / "trace.jsonl")
    tr = Tracer(path)
    with tr.span("a"):
        pass
    tr.close()
    out = str(tmp_path / "out.json")
    assert main(["timeline", path, "-o", out]) == 0
    assert json.load(open(out))["traceEvents"]
    capsys.readouterr()
    assert main(["timeline", str(tmp_path / "nope.jsonl"),
                 "-o", out]) == 2


# ----------------------------------------------- skelly-pulse: histograms

def test_log_histogram_percentiles_vs_numpy():
    """Percentile math against a numpy oracle on synthetic lognormal
    latencies: the geometric-interpolation estimate must sit within one
    bucket ratio of the true quantile."""
    from skellysim_tpu.obs.hist import LogHistogram

    rng = np.random.default_rng(42)
    vals = np.exp(rng.normal(np.log(0.05), 1.2, size=50000))
    h = LogHistogram(lo=1e-4, hi=1e3, per_decade=8)
    for v in vals:
        h.observe(v)
    ratio = 10.0 ** (1.0 / 8)   # one bucket edge step
    for q in (50.0, 90.0, 95.0, 99.0):
        est = h.percentile(q)
        true = float(np.percentile(vals, q))
        assert true / ratio <= est <= true * ratio, (q, est, true)
    s = h.summary()
    assert s["n"] == len(vals)
    assert s["mean"] == pytest.approx(float(vals.mean()))
    assert s["max"] == pytest.approx(float(vals.max()))
    assert s["p50"] <= s["p95"] <= s["p99"]


def test_log_histogram_edges_and_wire():
    from skellysim_tpu.obs.hist import (LogHistogram,
                                        render_prometheus_histogram)

    h = LogHistogram(lo=1e-3, hi=10.0, per_decade=4)
    assert h.summary() == {"n": 0, "mean": 0.0, "max": 0.0, "p50": 0.0,
                           "p95": 0.0, "p99": 0.0}
    for v in (0.0, 1e-5, 0.02, 0.02, 5.0, 1e9, float("nan")):
        h.observe(v)
    assert h.n == 7
    wire = h.to_wire()
    # cumulative buckets are monotone and terminate at +Inf == n
    counts = [c for _, c in wire["buckets"]]
    assert counts == sorted(counts)
    assert wire["buckets"][-1] == ["+Inf", 7] or \
        wire["buckets"][-1] == ("+Inf", 7)
    lines = render_prometheus_histogram("x_seconds", wire, help_text="t")
    assert lines[0] == "# HELP x_seconds t"
    assert lines[1] == "# TYPE x_seconds histogram"
    assert lines[-2] .startswith("x_seconds_sum ")
    assert lines[-1] == "x_seconds_count 7"
    assert any('le="+Inf"} 7' in ln for ln in lines)
    with pytest.raises(ValueError):
        LogHistogram(lo=1.0, hi=0.5)


# ------------------------------------------------ skelly-pulse: perf gate

def _write_round(dirpath, group, number, doc):
    p = os.path.join(str(dirpath), f"{group}_r{number:02d}.json")
    with open(p, "w") as fh:
        json.dump(doc, fh)
    return p


def test_perf_compare_gate_on_synthetic_rounds(tmp_path):
    from skellysim_tpu.obs.perf import render_report

    _write_round(tmp_path, "GROUPX", 1,
                 {"solve": {"d8": {"speedup_vs_1dev": 2.0}},
                  "rate": {"gpairs_per_s": 1.0}})
    _write_round(tmp_path, "GROUPX", 2,
                 {"solve": {"d8": {"speedup_vs_1dev": 1.0}},
                  "rate": {"gpairs_per_s": 1.05}})
    report, rc = render_report(str(tmp_path), gate_pct=25.0)
    assert rc == 1
    assert "REGRESSION" in report and "-50.0%" in report
    # within the gate: passes
    report, rc = render_report(str(tmp_path), gate_pct=60.0)
    assert rc == 0 and "within gate" in report


def test_perf_compare_downscaled_rounds_warn_only(tmp_path):
    from skellysim_tpu.obs.perf import render_report

    _write_round(tmp_path, "TOY", 1, {"m": {"speedup_vs_1dev": 4.0}})
    _write_round(tmp_path, "TOY", 2, {"m": {"speedup_vs_1dev": 1.0},
                                      "downscaled": True})
    report, rc = render_report(str(tmp_path), gate_pct=25.0)
    assert rc == 0
    assert "WARN (downscaled" in report


def test_perf_compare_skips_unparseable_rounds(tmp_path):
    """The r01-r05 failure shells ({"rc": 124}) render as incomplete and
    the diff picks the latest two PARSEABLE rounds."""
    from skellysim_tpu.obs.perf import render_report, scan_rounds

    _write_round(tmp_path, "G", 1, {"rc": 124, "ok": False})
    _write_round(tmp_path, "G", 2, {"m": {"speedup_vs_1dev": 1.0}})
    _write_round(tmp_path, "G", 3, {"m": {"speedup_vs_1dev": 2.0}})
    rounds = scan_rounds(str(tmp_path))["g"]
    assert [r.parseable for r in rounds] == [False, True, True]
    report, rc = render_report(str(tmp_path), gate_pct=25.0)
    assert rc == 0
    assert "incomplete" in report
    assert "diff r02 -> r03" in report
    # a single parseable round: trajectory only, nothing to diff
    two = tmp_path / "single"
    two.mkdir()
    _write_round(two, "G", 1, {"m": {"speedup_vs_1dev": 1.0}})
    report, rc = render_report(str(two), gate_pct=25.0)
    assert rc == 0 and "nothing to diff" in report


def test_perf_real_benchmarks_trajectory():
    """Acceptance pin: `obs perf --compare benchmarks/` renders the
    r01..r08 multichip trajectory and the gate passes on the checked-in
    (downscaled) rounds."""
    from skellysim_tpu.obs.perf import render_report

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    report, rc = render_report(os.path.join(repo, "benchmarks"))
    assert rc == 0
    assert "== multichip trajectory (8 round(s)) ==" in report
    for label in ("r01", "r07", "r08"):
        assert label in report
    assert "diff r07 -> r08" in report
    assert "coupled_spmd.d8.speedup_vs_1dev: 0.44 -> 0.63" in report
    # the vs-best column engages on the full history (r06 still holds the
    # matvec.d4 best on the oversubscribed virtual mesh)
    assert "best 3@r06" in report


def test_perf_cli_exit_codes(tmp_path, capsys):
    from skellysim_tpu.obs.cli import main

    _write_round(tmp_path, "G", 1, {"m": {"speedup_vs_1dev": 2.0}})
    _write_round(tmp_path, "G", 2, {"m": {"speedup_vs_1dev": 1.0}})
    assert main(["perf", "--compare", str(tmp_path)]) == 1
    assert main(["perf", "--compare", str(tmp_path), "--gate", "60"]) == 0
    capsys.readouterr()  # drain the text reports before the JSON one
    assert main(["perf", "--compare", str(tmp_path), "--json"]) == 1
    doc = json.loads(capsys.readouterr().out)
    assert doc["groups"]["g"]["diff"]["metrics"][0]["regressed"]
    empty = tmp_path / "empty"
    empty.mkdir()
    assert main(["perf", "--compare", str(empty)]) == 2
    assert main(["perf", "--compare", str(tmp_path / "nope")]) == 2
    assert main(["perf"]) == 2


# ------------------------------- skelly-pulse: provenance + summarize extras

def test_tracer_header_carries_provenance():
    """The telemetry header self-describes runtime + hardware (jax is
    imported in this process, so real values, not placeholders)."""
    from skellysim_tpu.obs.tracer import provenance

    tr = Tracer()
    header = tr.events[0]
    assert header["ev"] == "telemetry"
    assert header["jax_version"] == jax.__version__
    assert header["device_kind"]  # "cpu" on the test platform
    assert provenance(downscaled=True)["downscaled"] is True
    assert "downscaled" not in provenance()


def test_summarize_multifile_source_columns(tmp_path):
    """Several --trace-files summarize with per-file provenance on the
    span and lane-occupancy tables; a single file keeps the old layout."""
    from skellysim_tpu.obs.summarize import summarize_files

    def write(name, rounds):
        p = str(tmp_path / name)
        tr = Tracer(p)
        for i in range(rounds):
            with tr.span("ensemble_step", round=i, live=2, lanes=4):
                pass
        tr.close()
        return p

    a = write("serve_a.jsonl", 2)
    b = write("serve_b.jsonl", 3)
    single = summarize_files([a])
    assert "source" not in single.split("== spans ==")[1].splitlines()[1]
    assert "rounds: 2  lanes: 4" in single

    multi = summarize_files([a, b])
    span_header = multi.split("== spans ==")[1].splitlines()[1]
    assert span_header.startswith("source")
    assert "serve_a.jsonl" in multi and "serve_b.jsonl" in multi
    assert "[serve_a.jsonl] rounds: 2" in multi
    assert "[serve_b.jsonl] rounds: 3" in multi
