"""Row-sharded shell operators: coupled fiber+shell solve on the 8-device mesh
matches the single-program solve.

Mirrors the reference's periphery row decomposition
(`periphery.cpp:408-442`: shell operator rows Scatterv'd, matvec =
Allgatherv + local GEMV) with GSPMD row sharding.
"""

import pytest
import jax
import jax.numpy as jnp
import numpy as np

from skellysim_tpu.fibers import container as fc
from skellysim_tpu.params import Params
from skellysim_tpu.parallel import make_mesh, shard_state, use_mesh
from skellysim_tpu.periphery import periphery as peri
from skellysim_tpu.periphery.precompute import precompute_periphery
from skellysim_tpu.system import System

N_DEV = 8


def _coupled_state(system, shell_data, n_fibers=8, n_nodes=16):
    rng = np.random.default_rng(2)
    t = np.linspace(0, 1, n_nodes)
    # fibers inside the radius-4 shell, pointing inward from random origins
    origins = rng.uniform(-1.5, 1.5, size=(n_fibers, 3))
    dirs = rng.normal(size=(n_fibers, 3))
    dirs /= np.linalg.norm(dirs, axis=1, keepdims=True)
    x = origins[:, None, :] + t[None, :, None] * dirs[:, None, :]
    fibers = fc.make_group(x, lengths=1.0, bending_rigidity=0.01, radius=0.0125,
                           force_scale=-0.1, dtype=jnp.float64)
    shell = peri.make_state(shell_data["nodes"], shell_data["normals"],
                            shell_data["quadrature_weights"],
                            shell_data["stresslet_plus_complementary"],
                            shell_data["M_inv"])
    return system.make_state(fibers=fibers, shell=shell)


@pytest.mark.slow  # heavy coupled-solve integration; sibling fast tests keep the seam covered (ISSUE-9 870s-budget re-triage)
def test_sharded_shell_solve_matches_replicated():
    # 3*96 = 288 rows divide the 8-device mesh evenly
    shell_data = precompute_periphery("sphere", n_nodes=96, radius=4.0,
                                      eta=1.0)
    params = Params(eta=1.0, dt_initial=1e-3, t_final=1e-2, gmres_tol=1e-10,
                    adaptive_timestep_flag=False)
    shape = peri.PeripheryShape(kind="sphere", radius=4.0)

    sys_ref = System(params, shell_shape=shape)
    s_ref, sol_ref, info_ref = sys_ref.step(_coupled_state(sys_ref, shell_data))
    assert bool(info_ref.converged)

    mesh = make_mesh(N_DEV)
    sys_sh = System(params, shell_shape=shape)
    state = shard_state(_coupled_state(sys_sh, shell_data), mesh)
    # the dense operators really are distributed row-wise
    assert len(state.shell.M_inv.sharding.device_set) == N_DEV
    with use_mesh(mesh):
        s_sh, sol_sh, info_sh = sys_sh.step(state)
        jax.block_until_ready(sol_sh)

    assert bool(info_sh.converged)
    np.testing.assert_allclose(np.asarray(sol_sh), np.asarray(sol_ref),
                               atol=1e-9)
    np.testing.assert_allclose(np.asarray(s_sh.fibers.x),
                               np.asarray(s_ref.fibers.x), atol=1e-11)
    np.testing.assert_allclose(np.asarray(s_sh.shell.density),
                               np.asarray(s_ref.shell.density), atol=1e-9)


def test_indivisible_shell_rows_raise():
    """Silent O(n^2)-replication fallback is forbidden (VERDICT weak #3): an
    indivisible shell row count must fail with an actionable message."""
    import pytest

    shell_data = precompute_periphery("sphere", n_nodes=100, radius=4.0,
                                      eta=1.0)  # 300 rows % 8 != 0
    params = Params(eta=1.0, dt_initial=1e-3, t_final=1e-2, gmres_tol=1e-10,
                    adaptive_timestep_flag=False)
    shape = peri.PeripheryShape(kind="sphere", radius=4.0)
    sys_sh = System(params, shell_shape=shape)
    mesh = make_mesh(N_DEV)
    state = _coupled_state(sys_sh, shell_data)
    with pytest.raises(ValueError, match="multiple of 8"):
        shard_state(state, mesh)
    # explicit opt-in replicates instead
    sharded = shard_state(state, mesh, allow_replicated_shell=True)
    assert len(sharded.shell.M_inv.sharding.device_set) in (1, N_DEV)


def test_schema_placement_ignores_shape_collision():
    """Placement is schema-driven off field names, not shapes: a shell
    density whose length happens to equal a bucket's n_fibers must stay
    replicated (the old shape-sniffing heuristic fiber-sharded any
    [n_fibers]-long leaf, mis-sharding replicated shell vectors)."""
    # 16-node shell -> density [48]; 48 fibers (divisible by the 8-mesh):
    # the collision the old heuristic tripped on
    from skellysim_tpu.fibers import container as fc
    from skellysim_tpu.testing import make_coupled_parts

    shell, shape, _ = make_coupled_parts(16, 50, jnp.float64)
    params = Params(eta=1.0, dt_initial=1e-3, t_final=1e-2, gmres_tol=1e-10,
                    adaptive_timestep_flag=False)
    system = System(params, shell_shape=shape)
    rng = np.random.default_rng(3)
    nf, n_nodes = 48, 16
    t = np.linspace(0, 1, n_nodes)
    x = (rng.uniform(-1.5, 1.5, size=(nf, 3))[:, None, :]
         + t[None, :, None] * np.array([0.0, 0.0, 1.0])[None, None, :])
    fibers = fc.make_group(x, lengths=1.0, bending_rigidity=0.01,
                           radius=0.0125, dtype=jnp.float64)
    state = system.make_state(fibers=fibers, shell=shell)
    assert state.shell.density.shape[0] == state.fibers.n_fibers  # collision

    mesh = make_mesh(N_DEV)
    sharded = shard_state(state, mesh)
    # shell vectors replicate by schema regardless of the shape collision
    assert len(sharded.shell.density.sharding.device_set) == 1 \
        or sharded.shell.density.sharding.is_fully_replicated
    assert sharded.shell.weights.sharding.is_fully_replicated
    # the fiber bucket and the shell operator rows still shard
    assert len(sharded.fibers.x.sharding.device_set) == N_DEV
    assert not sharded.fibers.x.sharding.is_fully_replicated
    assert len(sharded.shell.M_inv.sharding.device_set) == N_DEV
    assert not sharded.shell.M_inv.sharding.is_fully_replicated


def test_multihost_initialize_noop_single_process():
    """Single-process runs skip distributed init and report sane process
    info (the multi-host bring-up path, parallel/multihost.py)."""
    from skellysim_tpu.parallel import multihost

    assert multihost.initialize() is False
    info = multihost.process_info()
    assert info["process_index"] == 0
    assert info["process_count"] == 1
    assert info["global_device_count"] >= 1
