"""Sweep spec (config.sweep) + the `python -m skellysim_tpu.ensemble` driver.

Spec expansion is pure host logic (fast, exhaustive); the driver test runs a
real free-fiber sweep in-process: base config -> members -> continuous
batching -> per-member reference-format trajectories + aggregated metrics.
"""

import json
import os

import numpy as np
import pytest

from skellysim_tpu.config import (Config, BackgroundSource, Fiber,
                                  apply_overrides, expand_members,
                                  load_sweep)
from skellysim_tpu.config.schema import EnsembleSweep, SweepAxis


def _base_config(tmp_path, t_final=0.02):
    cfg = Config()
    cfg.params.eta = 1.0
    cfg.params.dt_initial = 0.005
    cfg.params.dt_write = 0.005
    cfg.params.t_final = t_final
    cfg.params.gmres_tol = 1e-10
    cfg.params.adaptive_timestep_flag = False
    cfg.params.seed = 42
    fib = Fiber(n_nodes=8, length=1.0, bending_rigidity=0.01)
    fib.fill_node_positions(np.zeros(3), np.array([0.0, 0.0, 1.0]))
    cfg.fibers = [fib]
    cfg.background = BackgroundSource(uniform=[1.0, 0.0, 0.0])
    path = str(tmp_path / "skelly_config.toml")
    cfg.save(path)
    return cfg, path


def _sweep_file(tmp_path, body: str) -> str:
    path = str(tmp_path / "ensemble.toml")
    with open(path, "w") as fh:
        fh.write(body)
    return path


def test_load_sweep_and_validation(tmp_path):
    path = _sweep_file(tmp_path, """
[ensemble]
base_config = "skelly_config.toml"
replicas = 2
batch = 4
seed = 9
t_final = 0.01

[[ensemble.sweep]]
key = "fibers.0.length"
values = [1.0, 1.25]
""")
    spec = load_sweep(path)
    assert (spec.replicas, spec.batch, spec.seed, spec.t_final) == (2, 4, 9,
                                                                    0.01)
    assert [ax.key for ax in spec.sweep] == ["fibers.0.length"]

    with pytest.raises(ValueError, match="missing \\[ensemble\\]"):
        load_sweep(_sweep_file(tmp_path, "[other]\nx = 1\n"))
    with pytest.raises(ValueError, match="unknown \\[ensemble\\] keys"):
        load_sweep(_sweep_file(tmp_path, "[ensemble]\nreplicass = 2\n"))
    with pytest.raises(ValueError, match="batch_impl"):
        load_sweep(_sweep_file(tmp_path,
                               "[ensemble]\nbatch_impl = 'pmap'\n"))
    with pytest.raises(ValueError, match="static runtime Params"):
        load_sweep(_sweep_file(tmp_path, """
[ensemble]
[[ensemble.sweep]]
key = "params.eta"
values = [1.0, 2.0]
"""))


def test_expand_members_cartesian_replicas(tmp_path):
    base, _ = _base_config(tmp_path)
    spec = EnsembleSweep(
        replicas=2, seed=-1, t_final=-1.0,
        sweep=[SweepAxis(key="fibers.0.length", values=[1.0, 1.25]),
               SweepAxis(key="fibers.0.bending_rigidity",
                         values=[0.01, 0.02, 0.03])])
    plans = expand_members(spec, base)
    assert len(plans) == 2 * 2 * 3
    assert [p.member_id for p in plans[:3]] == ["m00000", "m00001", "m00002"]
    assert all(p.index == i for i, p in enumerate(plans))
    # seed/t_final default to the base config's
    assert all(p.seed == 42 for p in plans)
    assert all(p.t_final == base.params.t_final for p in plans)
    # every cartesian point appears replicas times
    points = {(p.overrides["fibers.0.length"],
               p.overrides["fibers.0.bending_rigidity"]) for p in plans}
    assert len(points) == 6


def test_apply_overrides_paths(tmp_path):
    base, _ = _base_config(tmp_path)
    out = apply_overrides(base, {"fibers.0.length": 2.0,
                                 "background.uniform.1": 0.5})
    assert out.fibers[0].length == 2.0
    assert out.background.uniform[1] == 0.5
    # the base is untouched (deep copy)
    assert base.fibers[0].length == 1.0 and base.background.uniform[1] == 0.0
    with pytest.raises(ValueError, match="no\\s+field"):
        apply_overrides(base, {"fibers.0.lenght": 2.0})
    with pytest.raises(ValueError, match="out of range"):
        apply_overrides(base, {"fibers.3.length": 2.0})
    with pytest.raises(ValueError, match="static runtime Params"):
        apply_overrides(base, {"params.gmres_tol": 1e-6})


def test_ensemble_cli_end_to_end(tmp_path):
    """Sweep -> trajectories: 2 lengths x 2 replicas through 2 lanes, then
    every member trajectory reads back with the right geometry and its own
    RNG stream, and the metrics JSONL segments by member."""
    from skellysim_tpu.ensemble import cli as ens_cli
    from skellysim_tpu.io.trajectory import TrajectoryReader

    _base_config(tmp_path)
    sweep = _sweep_file(tmp_path, """
[ensemble]
base_config = "skelly_config.toml"
replicas = 2
batch = 2
t_final = 0.01

[[ensemble.sweep]]
key = "fibers.0.length"
values = [1.0, 1.25]
""")
    out_dir = str(tmp_path / "out")
    retired = ens_cli.run(sweep, output_dir=out_dir)
    assert sorted(retired) == [f"m{i:05d}" for i in range(4)]

    lengths = []
    rng_states = set()
    for i in range(4):
        r = TrajectoryReader(os.path.join(out_dir, f"m{i:05d}.out"))
        assert len(r) >= 2  # initial frame + at least one dt_write frame
        frame = r.load_frame(-1)
        lengths.append(frame["fibers"][1][0]["length_"])
        rng_states.add(json.dumps(frame["rng_state"]))
        # uniform background advected the fiber: x drifted by u * t
        x0 = np.asarray(r.load_frame(0)["fibers"][1][0]["x_"]).reshape(-1, 3)
        x1 = np.asarray(frame["x_"] if "x_" in frame else
                        frame["fibers"][1][0]["x_"]).reshape(-1, 3)
        np.testing.assert_allclose(x1[:, 0] - x0[:, 0],
                                   frame["time"] - r.load_frame(0)["time"],
                                   atol=1e-10)
        r.close()
    assert sorted(lengths) == [1.0, 1.0, 1.25, 1.25]
    assert len(rng_states) == 4, "each member must carry its own RNG stream"

    with open(os.path.join(out_dir, "ensemble_metrics.jsonl")) as fh:
        records = [json.loads(ln) for ln in fh]
    by_event = {}
    for r in records:
        by_event.setdefault(r["event"], []).append(r)
    assert len(by_event["start"]) == len(by_event["retire"]) == 4
    assert {r["member"] for r in by_event["step"]} == set(retired)

    # clobber guard: a second run without --overwrite refuses up front
    with pytest.raises(SystemExit, match="already exist"):
        ens_cli.run(sweep, output_dir=out_dir)
