"""Multi-process (DCN-analogue) execution of the sharded evaluator.

The reference exercises its MPI path with real 2-rank ctest runs
(`/root/reference/tests/core/unit_tests/CMakeLists.txt:12-19,46-54`); this is
the jax.distributed equivalent: two OS processes, 2 virtual CPU devices
each, one global 4-device mesh, a ring-evaluator sum whose
collective-permutes cross the process boundary. Run as real subprocesses so
the coordinator/client handshake in `parallel.multihost.initialize` is
executed for real, not mocked.
"""

import os
import socket
import subprocess
import sys

import pytest


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_two_process_ring_evaluator(tmp_path):
    worker = os.path.join(os.path.dirname(__file__), "multihost_worker.py")
    port = _free_port()
    env = dict(os.environ)
    # replace any site hook that would register a (wedgeable) TPU platform
    # with just the repo root, and pin the CPU platform per process
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo_root
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"

    procs = [subprocess.Popen(
        [sys.executable, worker, str(port), str(pid)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env, cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        for pid in (0, 1)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail("multihost workers timed out; outputs so far: "
                    + "\n---\n".join(outs))
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out}"
        assert f"MULTIHOST-OK {pid}" in out, out
