"""skelly-guard: health verdicts, escalation ladder, quarantine, chaos.

Pins the ISSUE-9 robustness contracts (docs/robustness.md):

* the packed health word's bit semantics on real solver failure modes —
  nonfinite poisoning, zero-preconditioner stagnation, s-step
  Cholesky-ridge breakdown — computed device-side (no host sync) and
  batching under vmap;
* the escalation ladder's mechanics (bounded retries, dt_min floor,
  block_s/f64 fallbacks) on a scripted stub system — cheap and exact —
  plus one real-system integration (slow tier);
* chaos injectors: lane poisoning preserves shapes/dtypes, frame
  garbling/truncation/oversizing produce the documented wire behavior;
* `obs summarize`'s fault table and health-flagged step reporting.
"""

import json
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from skellysim_tpu.guard import chaos, escalate, verdict
from skellysim_tpu.solver.gmres import gmres, gmres_ir

jax.config.update("jax_enable_x64", True)


# ------------------------------------------------------------ verdict word

def test_verdict_bits_disjoint_and_decodable():
    bits = list(verdict.HEALTH_BITS.values())
    assert len(set(bits)) == len(bits)
    acc = 0
    for b in bits:
        assert b & acc == 0, "overlapping health bits"
        acc |= b
    assert verdict.decode(0) == []
    assert verdict.describe(0) == "ok"
    word = verdict.NONFINITE | verdict.STAGNATION
    assert verdict.decode(word) == ["nonfinite", "stagnation"]
    assert verdict.describe(word) == "nonfinite|stagnation"


def test_verdict_terminal_vs_retryable():
    assert bool(verdict.is_terminal(verdict.NONFINITE))
    assert bool(verdict.is_terminal(verdict.DT_UNDERFLOW))
    assert not bool(verdict.is_terminal(verdict.STAGNATION))
    assert not bool(verdict.retryable(0))
    assert bool(verdict.retryable(verdict.STAGNATION))
    assert bool(verdict.retryable(verdict.BREAKDOWN | verdict.STAGNATION))
    # terminal bits poison retryability even when combined with retryable
    assert not bool(verdict.retryable(verdict.NONFINITE
                                      | verdict.STAGNATION))


# ------------------------------------------------------ solver health word

def _problem(n=16, seed=0, dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    A = jnp.asarray(np.eye(n) + 0.1 * rng.standard_normal((n, n)),
                    dtype=dtype)
    b = jnp.asarray(rng.standard_normal(n), dtype=dtype)
    return A, b


def test_gmres_health_zero_on_healthy_solve():
    A, b = _problem()
    r = gmres(lambda x: A @ x, b, tol=1e-4, restart=8, maxiter=32)
    assert int(r.health) == 0 and bool(r.converged)


def test_gmres_health_nonfinite_rhs():
    """A NaN RHS short-circuits the solve through the b_norm guards (zero
    trips, x=0, 'converged') — exactly the silent poisoning the health
    word must surface."""
    A, b = _problem()
    r = gmres(lambda x: A @ x, b.at[0].set(jnp.nan), tol=1e-4, restart=8,
              maxiter=32)
    assert int(r.health) & verdict.NONFINITE


def test_gmres_health_stagnation_zero_preconditioner():
    """M=0 collapses the implicit residual through degenerate Givens
    rotations while x never moves: the implicit/explicit divergence Belos
    warns about, now a STAGNATION verdict."""
    A, b = _problem()
    r = gmres(lambda x: A @ x, b, precond=lambda v: v * 0.0, tol=1e-4,
              restart=4, maxiter=8)
    assert int(r.health) & verdict.STAGNATION
    assert float(r.residual_true) > 0.1  # x really did not move


def test_gmres_health_breakdown_rank_deficient_block():
    """A rank-1 operator kills the s-step monomial basis at the second
    candidate: the Cholesky-ridge column recovery must flag BREAKDOWN,
    not fabricate directions."""
    rng = np.random.default_rng(0)
    u = rng.standard_normal(16)
    u /= np.linalg.norm(u)
    A = jnp.asarray(np.outer(u, u), dtype=jnp.float32)
    b = jnp.asarray(rng.standard_normal(16), dtype=jnp.float32)
    r = gmres(lambda x: A @ x, b, tol=1e-6, restart=8, maxiter=16,
              block_s=4)
    assert int(r.health) & verdict.BREAKDOWN
    assert not bool(r.converged)


def test_gmres_health_batches_under_vmap():
    """One poisoned member must not flag its batched siblings — the word
    is an ordinary per-member carry."""
    A, b = _problem()
    bb = jnp.stack([b, b.at[0].set(jnp.nan), b])
    rr = jax.vmap(lambda bi: gmres(lambda x: A @ x, bi, tol=1e-4,
                                   restart=8, maxiter=32))(bb)
    health = np.asarray(rr.health)
    assert health[0] == 0 and health[2] == 0
    assert health[1] & verdict.NONFINITE


def test_gmres_ir_health():
    """gmres_ir: healthy == 0; poisoned RHS flags NONFINITE; the inner
    f32 loop's routine noise-floor stall must NOT mark the sweep
    stagnant when refinement still converges."""
    A, b = _problem(dtype=jnp.float64)
    r = gmres_ir(lambda x: A @ x, lambda x: A @ x, b, tol=1e-10,
                 inner_tol=1e-5, restart=16, maxiter=64)
    assert bool(r.converged) and int(r.health) == 0
    r = gmres_ir(lambda x: A @ x, lambda x: A @ x, b.at[0].set(jnp.nan),
                 tol=1e-10, inner_tol=1e-5, restart=16, maxiter=64)
    assert int(r.health) & verdict.NONFINITE


# -------------------------------------------------------- escalation ladder

class _StubParams:
    """Just the knobs `escalate` reads."""

    def __init__(self, **kw):
        self.guard_dt_halvings = kw.get("guard_dt_halvings", 0)
        self.guard_block_fallback = kw.get("guard_block_fallback", False)
        self.guard_f64_fallback = kw.get("guard_f64_fallback", False)
        self.gmres_block_s = kw.get("gmres_block_s", 1)
        self.adaptive_timestep_flag = kw.get("adaptive_timestep_flag", True)
        self.dt_min = kw.get("dt_min", 1e-4)
        self.gmres_tol = kw.get("gmres_tol", 1e-10)


class _StubState(NamedTuple):
    """Minimal pytree with `.dt` and `._replace(dt=...)`."""

    dt: jnp.ndarray


class _StubSystem:
    """Scripted solve: unhealthy until dt < `heal_below` (and/or until a
    requested fallback), so ladder mechanics are testable exactly and
    cheaply. `_solve_once` mirrors the real signature."""

    def __init__(self, params, heal_below=None, heal_on=None):
        self.params = params
        self.heal_below = heal_below
        self.heal_on = heal_on      # "block" | "full" | None
        self.calls = []

    def _precision_for(self, state):
        return "mixed"

    def _solve_once(self, state, pair=None, pair_anchors=None,
                    block_s=None, force_full=False):
        from skellysim_tpu.system.system import StepInfo

        self.calls.append((block_s, force_full))
        healed = False
        if self.heal_below is not None:
            healed = healed | (state.dt < self.heal_below)
        if self.heal_on == "block":
            healed = healed or (block_s == 1)
        if self.heal_on == "full":
            healed = healed or force_full
        health = jnp.where(jnp.asarray(healed), jnp.int32(0),
                           jnp.int32(verdict.STAGNATION))
        # an unhealthy attempt also shows an unconverged explicit residual
        # (the ladder's needs_retry gates on residual_true > gmres_tol, so
        # a breakdown-bit-with-converged-restart solve is NOT retried)
        resid_true = jnp.where(jnp.asarray(healed), jnp.float64(0.0),
                               jnp.float64(1.0))
        info = StepInfo(converged=health == 0, iters=jnp.int32(1),
                        residual=jnp.float64(0.0),
                        fiber_error=jnp.float64(0.0),
                        residual_true=resid_true,
                        loss_of_accuracy=jnp.asarray(False),
                        health=health, dt_used=state.dt)
        return _StubState(jnp.asarray(state.dt)), state.dt * 0.0, info


def _run_ladder(system, dt=0.1):
    state = _StubState(jnp.asarray(dt, dtype=jnp.float64))
    first = system._solve_once(state)
    return escalate.escalate(system, state, first)


def test_ladder_healthy_pays_nothing():
    sys_ = _StubSystem(_StubParams(guard_dt_halvings=3), heal_below=1.0)
    _, _, info = _run_ladder(sys_, dt=0.1)
    assert int(info.guard_retries) == 0
    assert float(info.dt_used) == 0.1
    assert int(info.health) == 0


def test_ladder_halves_dt_until_healthy():
    sys_ = _StubSystem(_StubParams(guard_dt_halvings=4), heal_below=0.03)
    _, _, info = _run_ladder(sys_, dt=0.1)
    # 0.1 -> 0.05 -> 0.025 (< 0.03: healed)
    assert int(info.guard_retries) == 2
    assert np.isclose(float(info.dt_used), 0.025)
    assert int(info.health) == 0


def test_ladder_bounded_and_verdict_survives():
    sys_ = _StubSystem(_StubParams(guard_dt_halvings=2), heal_below=0.0)
    _, _, info = _run_ladder(sys_, dt=0.1)
    assert int(info.guard_retries) == 2
    assert int(info.health) & verdict.STAGNATION


def test_ladder_respects_dt_min_floor():
    sys_ = _StubSystem(_StubParams(guard_dt_halvings=8, dt_min=0.04),
                       heal_below=0.0)
    _, _, info = _run_ladder(sys_, dt=0.1)
    # 0.1 -> 0.05; halving again would cross dt_min=0.04: stop
    assert int(info.guard_retries) == 1
    assert np.isclose(float(info.dt_used), 0.05)


def test_ladder_block_and_full_fallbacks():
    sys_ = _StubSystem(_StubParams(guard_block_fallback=True,
                                   gmres_block_s=4), heal_on="block")
    _, _, info = _run_ladder(sys_)
    assert int(info.health) == 0 and int(info.guard_retries) == 1
    assert (1, False) in sys_.calls

    sys_ = _StubSystem(_StubParams(guard_f64_fallback=True), heal_on="full")
    _, _, info = _run_ladder(sys_)
    assert int(info.health) == 0 and int(info.guard_retries) == 1
    assert any(ff for _, ff in sys_.calls)


def test_ladder_skips_breakdown_that_still_converged():
    """A BREAKDOWN bit can ride a solve whose restart converged anyway
    (gmres sets it 'either way'); re-solving those would waste full
    solves and perturb dt on healthy steps — the retry gate is the
    explicit residual, and the bit survives for telemetry."""
    class _ConvergedBrk(_StubSystem):
        def _solve_once(self, state, **kw):
            out = super()._solve_once(state, **kw)
            info = out[2]._replace(health=jnp.int32(verdict.BREAKDOWN),
                                   converged=jnp.asarray(True),
                                   residual_true=jnp.float64(0.0))
            return out[0], out[1], info

    sys_ = _ConvergedBrk(_StubParams(guard_dt_halvings=4,
                                     guard_block_fallback=True,
                                     gmres_block_s=4))
    _, _, info = _run_ladder(sys_)
    assert int(info.guard_retries) == 0
    assert int(info.health) & verdict.BREAKDOWN


def test_ladder_nonfinite_is_not_retried():
    """Terminal verdicts skip the ladder entirely: no dt can repair a
    poisoned state, and burning retries on it would delay quarantine."""
    class _Nan(_StubSystem):
        def _solve_once(self, state, **kw):
            out = super()._solve_once(state, **kw)
            info = out[2]._replace(health=jnp.int32(verdict.NONFINITE))
            return out[0], out[1], info

    sys_ = _Nan(_StubParams(guard_dt_halvings=4, guard_block_fallback=True,
                            gmres_block_s=4, guard_f64_fallback=True))
    _, _, info = _run_ladder(sys_)
    assert int(info.guard_retries) == 0
    assert int(info.health) & verdict.NONFINITE


# ---------------------------------------------- in-mesh escalation verdict

def test_guard_armed_spmd_build_warns_and_analyzes_replication_safe():
    """The params.py guard_* follow-up note, de-folklored (ISSUE 11): a
    guard-armed `step_spmd_d2` build still warns (the ladder is NOT wired
    into the mesh program), but the replication analyzer proves the program
    it actually builds deadlock-free — zero findings, every replicated
    output verified. The warning therefore documents missing escalation
    WIRING, not a divergence risk; what runtime work remains is recorded in
    docs/robustness.md ("In-mesh escalation")."""
    from skellysim_tpu.audit import fixtures, repflow
    from skellysim_tpu.parallel import shard_state
    from skellysim_tpu.parallel.mesh import make_mesh
    from skellysim_tpu.parallel.spmd import build_spmd_step

    mesh = make_mesh(2)
    system = fixtures.make_system(gmres_block_s=4, guard_dt_halvings=2,
                                  guard_block_fallback=True)
    state = shard_state(fixtures.free_state(system), mesh)
    with pytest.warns(UserWarning, match="escalation is not applied"):
        fn = build_spmd_step(system, mesh, state, donate=False)
    report = repflow.analyze(fn.trace(state).jaxpr)
    assert report.findings == []
    assert len(report.regions) == 1
    assert report.regions[0].axes == ("fib",)
    assert report.regions[0].replicated_outputs > 0   # info word included


def test_in_mesh_escalation_pattern_analyzes_replication_safe():
    """The follow-up's open question, answered statically: the escalation
    ladder's retry `while_loop` — predicate on a psum-derived health
    verdict and residual (exactly `escalate.needs_retry`), body re-solving
    at dt/2 with collectives inside — analyzes REPLICATED inside
    `shard_map`. In-mesh escalation is provably replication-safe by the
    same analyzer that gates the audited programs; the remaining work is
    threading `_solve_once` overrides through `build_spmd_step` and paying
    the per-stage compile cost (docs/robustness.md)."""
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from skellysim_tpu.audit import repflow
    from skellysim_tpu.parallel.compat import shard_map
    from skellysim_tpu.parallel.mesh import FIBER_AXIS, make_mesh

    mesh = make_mesh(2)

    def inner(v):
        def solve(dt):
            # stand-in for _solve_once: a psum'd reduction (the rdot seam)
            # and a verdict word derived from the REPLICATED residual
            resid = lax.psum(jnp.sum(v * v), FIBER_AXIS) * dt
            health = jnp.where(resid > 0.5, jnp.int32(verdict.STAGNATION),
                               jnp.int32(0))
            return resid, health

        resid, health = solve(jnp.float64(1.0))

        def cond(c):
            tries, dt, r, h = c
            return (tries < 2) & verdict.retryable(h) & (r > 1e-3)

        def body(c):
            tries, dt, r, h = c
            r2, h2 = solve(dt * 0.5)
            return tries + 1, dt * 0.5, r2, h2

        tries, dt, resid, health = lax.while_loop(
            cond, body, (jnp.int32(0), jnp.float64(1.0), resid, health))
        return resid, health, tries

    fn = shard_map(inner, mesh=mesh, in_specs=(P(FIBER_AXIS),),
                   out_specs=(P(), P(), P()), check_vma=False)
    report = repflow.analyze(jax.jit(fn).trace(jnp.ones(8)).jaxpr)
    assert report.findings == []
    assert report.regions[0].replicated_outputs == 3


# ------------------------------------------------------------ real system

@pytest.mark.slow
def test_guard_ladder_on_real_system_stagnation():
    """Integration: a zero-preconditioner (stagnant) solve on a real
    System exhausts its dt halvings inside ONE jitted step; a poisoned
    state is terminal with zero retries."""
    from skellysim_tpu.audit import fixtures

    system = fixtures.make_system(guard_dt_halvings=2)
    chaos.zero_preconditioner(system)
    state = fixtures.free_state(system)
    _, _, info = system.step(state)
    assert int(info.guard_retries) == 2
    assert int(info.health) & verdict.STAGNATION
    assert np.isclose(float(info.dt_used), float(state.dt) / 4.0)

    system2 = fixtures.make_system(guard_dt_halvings=2)
    _, _, info2 = system2.step(chaos.poison_state(
        fixtures.free_state(system2)))
    assert int(info2.health) & verdict.NONFINITE
    assert int(info2.guard_retries) == 0


# ------------------------------------------------------------ chaos wire

def test_chaos_garble_and_truncate_and_oversize():
    from skellysim_tpu.serve import protocol

    payload = protocol.pack_message({"type": "stats"})
    garbled = chaos.garble_frame(payload, seed=3)
    assert garbled != payload and len(garbled) == len(payload)
    framed = protocol.HEADER.pack(len(payload)) + payload
    assert chaos.truncate_frame(framed, 5) == framed[:5]
    hdr = chaos.oversized_header(1 << 40)
    (size,) = protocol.HEADER.unpack(hdr)
    assert size == 1 << 40


def test_chaos_poison_state_keeps_shapes():
    """The poisoned state must still ride the same compiled program."""
    import jax.tree_util as jtu

    from skellysim_tpu.audit import fixtures

    system = fixtures.make_system()
    state = fixtures.free_state(system)
    bad = chaos.poison_state(state)
    la, lb = jtu.tree_leaves(state), jtu.tree_leaves(bad)
    assert [(x.shape, x.dtype) for x in map(jnp.asarray, la)] \
        == [(x.shape, x.dtype) for x in map(jnp.asarray, lb)]
    assert jtu.tree_structure(state) == jtu.tree_structure(bad)
    from skellysim_tpu.fibers import container as fc

    assert all(bool(jnp.isnan(g.x).all()) for g in fc.as_buckets(bad.fibers))


# ------------------------------------------------------------- summarize

def test_summarize_fault_table(tmp_path):
    from skellysim_tpu.obs.summarize import summarize_files

    p = tmp_path / "trace.jsonl"
    lines = [
        {"ev": "telemetry", "version": 1},
        {"ev": "fault", "kind": "lane_failed", "verdict": "nonfinite"},
        {"ev": "fault", "kind": "lane_failed", "verdict": "nonfinite"},
        {"ev": "fault", "kind": "fused_ring_fallback",
         "reason": "backend-cpu"},
        {"iters": 4, "accepted": True, "health": verdict.STAGNATION,
         "guard_retries": 2, "residual": 1e-5},
    ]
    p.write_text("\n".join(json.dumps(r) for r in lines) + "\n")
    out = summarize_files([str(p)])
    assert "== faults ==" in out
    assert "lane_failed" in out and "2" in out
    assert "fused_ring_fallback" in out
    assert "nonfinite=2" in out
    assert "HEALTH-FLAGGED steps: 1" in out
    assert "guard retries: 2" in out


def test_summarize_fault_legs_line(tmp_path):
    """Fused-ring fallbacks carry the eligibility leg that failed; the
    fault table renders the leg counts so "too big for VMEM" (budget)
    reads differently from "not a TPU" (platform)."""
    from skellysim_tpu.obs.summarize import summarize_files

    p = tmp_path / "trace.jsonl"
    lines = [
        {"ev": "telemetry", "version": 1},
        {"ev": "fault", "kind": "fused_ring_fallback",
         "reason": "backend-cpu", "leg": "platform"},
        {"ev": "fault", "kind": "fused_ring_fallback",
         "reason": "vmem-budget-stokeslet-4096x4096x8", "leg": "budget"},
        {"ev": "fault", "kind": "fused_ring_fallback",
         "reason": "vmem-budget-stresslet-4096x4096x8", "leg": "budget"},
    ]
    p.write_text("\n".join(json.dumps(r) for r in lines) + "\n")
    out = summarize_files([str(p)])
    assert "legs: budget=2, platform=1" in out
