"""Heterogeneous fiber-resolution buckets in one simulation.

The reference runs fibers of mixed node counts in one `std::list` container
(`/root/reference/src/core/fiber_finite_difference.cpp:519-562`); here each
resolution is a dense vmapped bucket and `SimState.fibers` is a tuple of
`FiberGroup`s. These tests pin:

* algebraic equivalence — splitting one group into two same-resolution
  buckets changes nothing (the strongest test of the bucket plumbing);
* mixed-resolution solves run end to end and decouple correctly at
  distance;
* the builder accepts mixed-n_nodes configs;
* trajectory round-trips preserve per-fiber resolutions and CONFIG order
  on the wire (`config_rank`), like the reference's declaration-order
  serialization.
"""

import pytest
import numpy as np
import jax.numpy as jnp

from skellysim_tpu.fibers import container as fc
from skellysim_tpu.params import Params
from skellysim_tpu.system import BackgroundFlow, System


def _straight_fibers(n_fib, n_nodes, origins, seed=5):
    rng = np.random.default_rng(seed)
    dirs = rng.normal(size=(n_fib, 3))
    dirs /= np.linalg.norm(dirs, axis=1, keepdims=True)
    t = np.linspace(0, 1.0, n_nodes)
    return origins[:, None, :] + t[None, :, None] * dirs[:, None, :]


def _params(tol=1e-10):
    return Params(eta=1.0, dt_initial=1e-3, t_final=1e-2, gmres_tol=tol,
                  adaptive_timestep_flag=False)


def test_same_resolution_bucket_split_is_exact():
    """[A|B] as one group == (A, B) as two buckets: identical layout,
    identical physics, bitwise-comparable solutions."""
    rng = np.random.default_rng(11)
    x = _straight_fibers(6, 16, rng.uniform(-2, 2, (6, 3)))
    bg = BackgroundFlow.make(uniform=(1.0, 0.0, 0.0))

    system = System(_params())
    one = fc.make_group(x, lengths=1.0, bending_rigidity=0.01, radius=0.0125)
    st_one = system.make_state(fibers=one, background=bg)
    _, sol_one, info_one = system.step(st_one)
    assert bool(info_one.converged)

    ga = fc.make_group(x[:4], lengths=1.0, bending_rigidity=0.01,
                       radius=0.0125, config_rank=np.arange(4))
    gb = fc.make_group(x[4:], lengths=1.0, bending_rigidity=0.01,
                       radius=0.0125, config_rank=np.arange(4, 6))
    st_two = system.make_state(fibers=(ga, gb), background=bg)
    _, sol_two, info_two = system.step(st_two)
    assert bool(info_two.converged)

    err = (np.linalg.norm(np.asarray(sol_two) - np.asarray(sol_one))
           / np.linalg.norm(np.asarray(sol_one)))
    assert err < 1e-12, err


def test_mixed_resolution_solve_decouples_at_distance():
    """A 32-node fiber and a 16-node fiber 500 apart in one mixed sim match
    their solo solves (hydrodynamic coupling ~1/r is below tolerance)."""
    x_hi = _straight_fibers(1, 32, np.zeros((1, 3)), seed=7)
    x_lo = _straight_fibers(1, 16, np.array([[500.0, 0.0, 0.0]]), seed=8)
    bg = BackgroundFlow.make(uniform=(0.0, 0.0, 1.0))
    system = System(_params())

    g_hi = fc.make_group(x_hi, lengths=1.0, bending_rigidity=0.01,
                         radius=0.0125)
    g_lo = fc.make_group(x_lo, lengths=1.0, bending_rigidity=0.01,
                         radius=0.0125, config_rank=np.array([1]))
    st = system.make_state(fibers=(g_hi, g_lo), background=bg)
    new_state, sol, info = system.step(st)
    assert bool(info.converged)
    size_hi = 4 * 32

    solo = {}
    for g in (fc.make_group(x_hi, lengths=1.0, bending_rigidity=0.01,
                            radius=0.0125),
              fc.make_group(x_lo, lengths=1.0, bending_rigidity=0.01,
                            radius=0.0125)):
        st1 = system.make_state(fibers=g, background=bg)
        _, sol1, info1 = system.step(st1)
        assert bool(info1.converged)
        solo[g.n_nodes] = np.asarray(sol1)

    sol = np.asarray(sol)
    err_hi = (np.linalg.norm(sol[:size_hi] - solo[32])
              / np.linalg.norm(solo[32]))
    err_lo = (np.linalg.norm(sol[size_hi:] - solo[16])
              / np.linalg.norm(solo[16]))
    assert err_hi < 1e-4, err_hi
    assert err_lo < 1e-4, err_lo
    # the stepped positions land in the right buckets
    assert new_state.fibers[0].n_nodes == 32
    assert new_state.fibers[1].n_nodes == 16


def test_builder_accepts_mixed_resolution_config(tmp_path):
    from skellysim_tpu import builder
    from skellysim_tpu.config import Config, Fiber

    cfg = Config()
    cfg.params.dt_initial = 1e-3
    cfg.params.t_final = 1e-2
    cfg.params.adaptive_timestep_flag = False
    for i, n in enumerate((16, 24, 16)):
        fib = Fiber(n_nodes=n, length=1.0, bending_rigidity=0.01)
        fib.fill_node_positions(np.array([2.0 * i, 0.0, 0.0]),
                                np.array([0.0, 0.0, 1.0]))
        cfg.fibers.append(fib)
    cfg.background.uniform = [0.0, 0.0, 1.0]
    path = str(tmp_path / "skelly_config.toml")
    cfg.save(path)

    system, state, _ = builder.build_simulation(path)
    assert isinstance(state.fibers, tuple)
    assert [g.n_nodes for g in state.fibers] == [16, 24]
    assert state.fibers[0].n_fibers == 2           # fibers 0 and 2
    np.testing.assert_array_equal(np.asarray(state.fibers[0].config_rank),
                                  [0, 2])
    np.testing.assert_array_equal(np.asarray(state.fibers[1].config_rank),
                                  [1])
    _, _, info = system.step(state)
    assert bool(info.converged)


def test_mixed_resolution_trajectory_roundtrip(tmp_path):
    """frame_bytes == packb(state_to_frame), fibers appear in CONFIG order
    with their own n_nodes, and frame_to_state rebuilds the same buckets."""
    import msgpack

    from skellysim_tpu.io import eigen
    from skellysim_tpu.io.trajectory import (TrajectoryReader,
                                             TrajectoryWriter,
                                             frame_bytes, frame_to_state,
                                             state_to_frame)

    x_hi = _straight_fibers(2, 24, np.array([[0.0, 0.0, 0.0],
                                             [4.0, 0.0, 0.0]]), seed=3)
    x_lo = _straight_fibers(1, 16, np.array([[2.0, 0.0, 0.0]]), seed=4)
    # config order: hi0 (rank 0), lo0 (rank 1), hi1 (rank 2)
    g_hi = fc.make_group(x_hi, lengths=1.0, bending_rigidity=0.01,
                         radius=0.0125, config_rank=np.array([0, 2]))
    g_lo = fc.make_group(x_lo, lengths=0.8, bending_rigidity=0.02,
                         radius=0.025, config_rank=np.array([1]))
    system = System(_params())
    state = system.make_state(fibers=(g_hi, g_lo),
                              background=BackgroundFlow.make(
                                  uniform=(0.0, 0.0, 1.0)))

    raw = frame_bytes(state)
    assert raw == msgpack.packb(state_to_frame(state))
    frame = eigen.decode_tree(msgpack.unpackb(raw, raw=False))
    n_by_pos = [f["n_nodes_"] for f in frame["fibers"][1]]
    assert n_by_pos == [24, 16, 24]               # config order on the wire

    path = str(tmp_path / "traj.out")
    with TrajectoryWriter(path) as tw:
        tw.write_frame(state)
    reader = TrajectoryReader(path)
    rebuilt = frame_to_state(reader.load_frame(0), state)
    assert isinstance(rebuilt.fibers, tuple)
    assert [g.n_nodes for g in rebuilt.fibers] == [24, 16]
    np.testing.assert_allclose(np.asarray(rebuilt.fibers[0].x),
                               np.asarray(g_hi.x))
    np.testing.assert_allclose(np.asarray(rebuilt.fibers[1].x),
                               np.asarray(g_lo.x))
    np.testing.assert_array_equal(
        np.asarray(rebuilt.fibers[0].config_rank), [0, 2])


# ----------------------------------------------------- heterogeneous bodies

def _sphere_body(n_nodes, position, radius=0.5, force=(0.0, 0.0, 1.0),
                 rank=None, n_sites=0, dtype=jnp.float64):
    from skellysim_tpu.bodies import bodies as bd
    from skellysim_tpu.periphery.precompute import precompute_body

    pre = precompute_body("sphere", n_nodes, radius=radius)
    sites = None
    if n_sites:
        t = np.linspace(0, 2 * np.pi, n_sites, endpoint=False)
        sites = np.stack([radius * np.cos(t), radius * np.sin(t),
                          np.zeros(n_sites)], axis=-1)[None]
    return bd.make_group(
        pre["node_positions_ref"], pre["node_normals_ref"],
        pre["node_weights"], position=np.asarray([position], dtype=float),
        nucleation_sites_ref=sites,
        external_force=np.asarray([force], dtype=float),
        radius=np.array([radius]), kind="sphere",
        config_rank=None if rank is None else np.array([rank]), dtype=dtype)


@pytest.mark.slow  # heavy coupled-solve integration; sibling fast tests keep the seam covered (ISSUE-9 870s-budget re-triage)
def test_same_kind_body_bucket_split_is_exact():
    """Two same-resolution sphere bodies as one batch == two buckets."""
    from skellysim_tpu.bodies import bodies as bd
    from skellysim_tpu.periphery.precompute import precompute_body

    pre = precompute_body("sphere", 150, radius=0.5)
    pos = np.array([[0.0, 0.0, -2.0], [0.0, 0.0, 2.0]])
    force = np.array([[0.0, 0.0, 1.0], [0.0, 0.0, -0.5]])
    system = System(_params())

    one = bd.make_group(np.stack([pre["node_positions_ref"]] * 2),
                        np.stack([pre["node_normals_ref"]] * 2),
                        np.stack([pre["node_weights"]] * 2),
                        position=pos, external_force=force,
                        radius=np.array([0.5, 0.5]), kind="sphere")
    _, sol_one, info1 = system.step(system.make_state(bodies=one))
    assert bool(info1.converged)

    ga = _sphere_body(150, pos[0], force=force[0], rank=0)
    gb = _sphere_body(150, pos[1], force=force[1], rank=1)
    _, sol_two, info2 = system.step(system.make_state(bodies=(ga, gb)))
    assert bool(info2.converged)
    err = (np.linalg.norm(np.asarray(sol_two) - np.asarray(sol_one))
           / np.linalg.norm(np.asarray(sol_one)))
    assert err < 1e-12, err


def test_mixed_body_resolutions_and_shapes():
    """A 150-node sphere + a 240-node ellipsoid in ONE sim (different
    buckets, the reference's mixed BodyContainer): both reproduce their
    isolated mobility oracles at large separation."""
    from skellysim_tpu.bodies import bodies as bd
    from skellysim_tpu.periphery.precompute import precompute_body

    a = b_ax = c = 0.4
    pre_e = precompute_body("ellipsoid", 240, a=a, b=b_ax, c=c)
    sphere = _sphere_body(150, [0.0, 0.0, -400.0], radius=0.5, rank=0)
    ellip = bd.make_group(
        pre_e["node_positions_ref"], pre_e["node_normals_ref"],
        pre_e["node_weights"], position=np.array([[0.0, 0.0, 400.0]]),
        external_force=np.array([[0.0, 0.0, 1.0]]), kind="ellipsoid",
        semiaxes=[a, b_ax, c], config_rank=np.array([1]))

    system = System(_params())
    state, _, info = system.step(system.make_state(bodies=(sphere, ellip)))
    assert bool(info.converged)

    eta = 1.0
    r_s = np.linalg.norm(np.asarray(sphere.nodes_ref)[0], axis=-1).mean()
    v_sphere = float(state.bodies[0].velocity[0, 2])
    v_th_s = 1.0 / (6 * np.pi * eta * r_s)
    # gate at the coarse-quadrature (150/240-node) discretization level
    assert abs(1 - v_sphere / v_th_s) < 1e-2, (v_sphere, v_th_s)

    r_e = np.linalg.norm(np.asarray(ellip.nodes_ref)[0], axis=-1).mean()
    v_ellip = float(state.bodies[1].velocity[0, 2])
    v_th_e = 1.0 / (6 * np.pi * eta * r_e)
    assert abs(1 - v_ellip / v_th_e) < 1e-2, (v_ellip, v_th_e)


def test_fiber_bound_to_second_body_bucket():
    """A fiber whose GLOBAL parent id points into the SECOND body bucket:
    link conditions + repin go through the global->local remap."""
    from skellysim_tpu.bodies import bodies as bd

    b0 = _sphere_body(100, [0.0, 0.0, -3.0], rank=0)
    b1 = _sphere_body(150, [0.0, 0.0, 3.0], rank=1, n_sites=4)

    # fiber clamped to body 1 (global id), site 0
    _, _, sites = bd.place(b1)
    origin = np.asarray(sites)[0, 0]
    u = origin - np.array([0.0, 0.0, 3.0])
    u /= np.linalg.norm(u)
    t = np.linspace(0, 0.6, 16)
    x = origin[None, :] + t[:, None] * u[None, :]
    fibers = fc.make_group(x[None], lengths=0.6, bending_rigidity=0.01,
                           radius=0.0125, minus_clamped=True,
                           binding_body=np.array([1]),
                           binding_site=np.array([0]))

    system = System(_params(tol=1e-9))
    state = system.make_state(fibers=fibers, bodies=(b0, b1))
    new_state, _, info = system.step(state)
    assert bool(info.converged)
    # minus end re-pinned onto body 1's (moved) site
    _, _, new_sites = bd.place(new_state.bodies[1])
    minus_end = np.asarray(new_state.fibers.x)[0, 0]
    np.testing.assert_allclose(minus_end, np.asarray(new_sites)[0, 0],
                               atol=1e-12)
    # body 1 moved (pulled by gravity-like force), body 0 moved independently
    assert abs(float(new_state.bodies[1].velocity[0, 2])) > 0


def test_mixed_bodies_trajectory_roundtrip():
    """Mixed body buckets serialize kind-grouped + config-ordered and
    restore into the same buckets."""
    import msgpack

    from skellysim_tpu.bodies import bodies as bd
    from skellysim_tpu.io import eigen
    from skellysim_tpu.io.trajectory import frame_bytes, frame_to_state, state_to_frame
    from skellysim_tpu.periphery.precompute import precompute_body

    pre_e = precompute_body("ellipsoid", 120, a=0.4, b=0.4, c=0.4)
    # config order: ellipsoid (rank 0), sphere (rank 1)
    ellip = bd.make_group(
        pre_e["node_positions_ref"], pre_e["node_normals_ref"],
        pre_e["node_weights"], position=np.array([[1.0, 0.0, 0.0]]),
        kind="ellipsoid", semiaxes=[0.4, 0.4, 0.4],
        config_rank=np.array([0]))
    sphere = _sphere_body(100, [-1.0, 0.0, 0.0], rank=1)
    system = System(_params())
    state = system.make_state(bodies=(ellip, sphere))

    raw = frame_bytes(state)
    assert raw == msgpack.packb(state_to_frame(state))
    frame = eigen.decode_tree(msgpack.unpackb(raw, raw=False))
    spheres, deformable, ellipsoids = frame["bodies"]
    assert len(spheres) == 1 and len(ellipsoids) == 1 and deformable == []

    # perturb then restore
    moved = frame
    rebuilt = frame_to_state(moved, state)
    np.testing.assert_allclose(np.asarray(rebuilt.bodies[0].position),
                               [[1.0, 0.0, 0.0]])
    np.testing.assert_allclose(np.asarray(rebuilt.bodies[1].position),
                               [[-1.0, 0.0, 0.0]])


@pytest.mark.slow  # heavy coupled-solve integration; sibling fast tests keep the seam covered (ISSUE-9 870s-budget re-triage)
def test_mixed_resolution_solve_through_pallas_seam():
    """kernel_impl="pallas" serves the multi-bucket union evaluator pass
    (`fc.flow_multi`) — interpret mode on CPU. f32 state so the f64
    fallback guard doesn't bypass the tile; agreement with the exact path
    is f32-rounding-grade. Exercises the padded-source invariant: inactive
    pad nodes ride the union pass with zero quadrature-weighted densities
    and must contribute exactly zero through the pallas tile."""
    rng = np.random.default_rng(17)
    xa = _straight_fibers(3, 16, rng.uniform(-2, 2, (3, 3)), seed=6)
    xb = _straight_fibers(2, 24, rng.uniform(-2, 2, (2, 3)), seed=7)
    bg = BackgroundFlow.make(uniform=(1.0, 0.0, 0.0), dtype=jnp.float32)

    def solve(impl):
        ga = fc.make_group(xa, lengths=1.0, bending_rigidity=0.01,
                           radius=0.0125, config_rank=np.arange(3),
                           dtype=jnp.float32)
        gb = fc.make_group(xb, lengths=1.0, bending_rigidity=0.01,
                           radius=0.0125, config_rank=np.arange(3, 5),
                           dtype=jnp.float32)
        params = Params(eta=1.0, dt_initial=1e-3, t_final=1e-2,
                        gmres_tol=1e-5, kernel_impl=impl,
                        adaptive_timestep_flag=False)
        system = System(params)
        st = system.make_state(fibers=(ga, gb), background=bg)
        _, sol, info = system.step(st)
        assert bool(info.converged), impl
        return np.asarray(sol)

    sol_p = solve("pallas")
    sol_x = solve("exact")
    err = np.linalg.norm(sol_p - sol_x) / np.linalg.norm(sol_x)
    assert err < 1e-3, err
