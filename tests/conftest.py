"""Test configuration: force an 8-device virtual CPU platform with float64.

Mirrors the reference's multi-rank-without-a-cluster strategy
(`/root/reference/tests/core/unit_tests/CMakeLists.txt:12-19`: ctest under
`mpiexec -n 2`): sharding correctness is exercised on a virtual device mesh, and
physics accuracy gates run in float64 on CPU.

The session environment registers the experimental `axon` TPU platform via a
sitecustomize hook; its client init goes through a tunnel that can block for
minutes, so CPU test runs unregister it entirely before JAX initializes any
backend.
"""

from skellysim_tpu.utils.bootstrap import force_cpu_devices

force_cpu_devices(8)

import jax  # noqa: E402

jax.config.update("jax_enable_x64", True)
