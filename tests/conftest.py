"""Test configuration: force an 8-device virtual CPU platform with float64.

Mirrors the reference's multi-rank-without-a-cluster strategy
(`/root/reference/tests/core/unit_tests/CMakeLists.txt:12-19`: ctest under
`mpiexec -n 2`): sharding correctness is exercised on a virtual device mesh, and
physics accuracy gates run in float64 on CPU. Must set env vars before jax import.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_enable_x64", True)
