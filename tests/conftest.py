"""Test configuration: force an 8-device virtual CPU platform with float64.

Mirrors the reference's multi-rank-without-a-cluster strategy
(`/root/reference/tests/core/unit_tests/CMakeLists.txt:12-19`: ctest under
`mpiexec -n 2`): sharding correctness is exercised on a virtual device mesh, and
physics accuracy gates run in float64 on CPU.

The session environment registers the experimental `axon` TPU platform via a
sitecustomize hook; its client init goes through a tunnel that can block for
minutes, so CPU test runs unregister it entirely before JAX initializes any
backend.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"  # override: the session env pins axon (TPU)
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

# Unregister the axon factory outright: JAX_PLATFORMS=cpu alone was observed NOT
# to prevent the axon client init (the sitecustomize hook routes get_backend
# through backends(), which then initializes axon and can block on the tunnel).
# Private API, so guard against jax-version drift.
try:
    import jax._src.xla_bridge as _xb  # noqa: E402

    _xb._backend_factories.pop("axon", None)
except Exception:
    pass

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
