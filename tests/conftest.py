"""Test configuration: force an 8-device virtual CPU platform with float64.

Mirrors the reference's multi-rank-without-a-cluster strategy
(`/root/reference/tests/core/unit_tests/CMakeLists.txt:12-19`: ctest under
`mpiexec -n 2`): sharding correctness is exercised on a virtual device mesh, and
physics accuracy gates run in float64 on CPU.

The session environment registers the experimental `axon` TPU platform via a
sitecustomize hook; its client init goes through a tunnel that can block for
minutes, so CPU test runs unregister it entirely before JAX initializes any
backend.
"""

from skellysim_tpu.utils.bootstrap import force_cpu_devices

force_cpu_devices(8)

import jax  # noqa: E402
import pytest  # noqa: E402

jax.config.update("jax_enable_x64", True)


@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches_per_module():
    """Drop compiled executables between test modules.

    A full-suite run compiles 250+ pjit programs into one process; with all
    of them held live, the XLA:CPU compiler segfaults nondeterministically
    around the ~85% mark (observed twice in round 5, inside
    backend_compile_and_load — the crash needs the accumulation: every
    individual module passes alone). Clearing per module caps the number of
    live executables; the recompiles it causes are per-module state anyway.
    """
    yield
    jax.clear_caches()
