"""skelly-scenario: device-side dynamic instability on the batched paths.

Pins the ISSUE-13 acceptance criteria:

* the device DI update (`scenarios.di_device`) applies EXACTLY the host
  oracle's nucleation/catastrophe update under injected deterministic
  draws (shared `system.di_rates` math; node geometry to XLA-vs-libm
  roundoff);
* a B-member confined (periphery + body + growing/shrinking fibers)
  dynamic-instability sweep runs on the ensemble vmap path with member
  trajectories matching sequential host-loop `System.run` executions at
  the vmap-plan tolerance (rtol 1e-9 — the same pin test_ensemble.py uses
  for vmap-vs-unroll);
* within-bucket nucleation/catastrophe produce ZERO `observed_jit`
  compile events, and a capacity overflow reseats onto the next bucket
  rung with exactly one new trace per rung (`trace_counting_jit`);
* guard quarantine semantics are intact under DI: a poisoned DI lane
  retires ``failed`` while its siblings' trajectories continue untouched.
"""

import dataclasses

import numpy as np
import pytest

import jax.numpy as jnp

from skellysim_tpu.bodies import bodies as bd
from skellysim_tpu.ensemble.runner import EnsembleRunner
from skellysim_tpu.ensemble.scheduler import EnsembleScheduler, MemberSpec
from skellysim_tpu.fibers import container as fc
from skellysim_tpu.obs import tracer as obs_tracer
from skellysim_tpu.params import DynamicInstability, Params
from skellysim_tpu.periphery.precompute import precompute_body
from skellysim_tpu.scenarios import (ScenarioEnsemble, di_device,
                                     ensure_di_capacity)
from skellysim_tpu.system import System, apply_dynamic_instability
from skellysim_tpu.testing import trace_counting_jit
from skellysim_tpu.utils.rng import SimRNG

N_SITES = 6
BODY_R = 0.5


@pytest.fixture(scope="module")
def body_group():
    pre = precompute_body("sphere", 40, radius=BODY_R)
    rng = np.random.default_rng(11)
    sites = rng.standard_normal((N_SITES, 3))
    sites = BODY_R * sites / np.linalg.norm(sites, axis=1, keepdims=True)
    return bd.make_group(pre["node_positions_ref"], pre["node_normals_ref"],
                         pre["node_weights"],
                         nucleation_sites_ref=sites[None], radius=BODY_R)


def di_params(**kw):
    di_kw = dict(n_nodes=8, v_growth=0.2, f_catastrophe=0.5,
                 nucleation_rate=60.0, min_length=0.4,
                 radius=0.0125, bending_rigidity=0.01)
    di_kw.update(kw.pop("di", {}))
    base = dict(eta=1.0, dt_initial=0.02, dt_write=0.02, t_final=0.08,
                gmres_tol=1e-10, adaptive_timestep_flag=False,
                dynamic_instability=DynamicInstability(**di_kw))
    base.update(kw)
    return Params(**base)


def seed_fibers(capacity=8, n_active=2, n_nodes=8, shift=0.0):
    """`n_active` live unbound fibers in a `capacity`-slot batch."""
    x = np.tile(np.linspace(0.0, 1.0, n_nodes)[None, :, None],
                (n_active, 1, 3))
    x += (1.5 + shift + np.arange(n_active))[:, None, None]
    g = fc.make_group(x, lengths=1.0, bending_rigidity=0.01, radius=0.0125)
    return fc.grow_capacity(g, capacity)


def device_group(g):
    """Round-trip every array leaf to a device array (grow_capacity edits
    host-side; stacked ensembles want jnp leaves)."""
    return type(g)(*[jnp.asarray(leaf) if name != "rt_mats"
                     and leaf is not None else leaf
                     for name, leaf in zip(g._fields, g)])


# ------------------------------------------------- injected deterministic draws
#
# One pseudo-draw schedule consumed by BOTH paths: the device sample_fn
# derives (member key, step) from the RNG carry the runner threads through
# the trace; the host stub mirrors it by counting its uniform() calls. Site
# priorities ascend, so the device argsort picks free sites in flat-table
# order — exactly the host's pop(j=0) sequence.

def _u(mkey, step, i):
    return ((mkey * 131 + step * 31 + i * 7) % 97) / 97.0


def _n_raw(mkey, step):
    return (mkey + step) % 3


def injected_sample_fn(di_rng, lam, capacity, n_sites, dtype):
    mkey = di_rng[1]
    step = di_rng[2] // di_device.DRAWS_PER_STEP
    u_cat = ((mkey * 131 + step * 31
              + jnp.arange(capacity, dtype=jnp.int32) * 7) % 97) / 97.0
    return di_device.DIDraws(
        u_cat=u_cat.astype(dtype),
        n_raw=((mkey + step) % 3).astype(jnp.int32),
        u_site=(jnp.arange(max(n_sites, 1), dtype=dtype)[:n_sites]
                / max(n_sites, 1)))


class _SeqStream:
    """Host mirror of `injected_sample_fn` with the real Stream's API."""

    def __init__(self, mkey, seed=0, stream_id=None, counter=0):
        self.mkey = mkey
        self.seed, self.stream_id = seed, mkey if stream_id is None else stream_id
        self.step = -1

    @property
    def counter(self):
        return max(self.step, 0) * di_device.DRAWS_PER_STEP

    def uniform(self, low=0.0, high=1.0, size=None):
        self.step += 1
        return np.array([_u(self.mkey, self.step, i) for i in range(size)])

    def poisson_int(self, lam, size=None):
        return int(_n_raw(self.mkey, self.step))

    def uniform_int(self, low, high, size=None):
        return 0

    def dump(self):
        return f"{self.seed}:{self.stream_id}:{self.counter}"


class _SeqRNG:
    def __init__(self, mkey):
        self.distributed = _SeqStream(mkey)
        self.shared = _SeqStream(mkey + 10_000)

    def dump_state(self):
        return [["shared", self.shared.dump()],
                ["distributed", self.distributed.dump()]]


def member_rng_pair(i, seed=5):
    """(device SimRNG, host mirror) for ensemble member ``i`` — the device
    carry's stream id (2i+3) is the shared member key."""
    return SimRNG(seed).member(i), _SeqRNG(2 * i + 3)


# ------------------------------------------------------------ update parity

def test_device_matches_host_injected_draws(body_group):
    """One DI update, same injected draws: every per-fiber field matches
    the host oracle bitwise except nucleated node geometry (XLA vs libm
    normalization, <= a few ulp)."""
    params = di_params()
    system = System(params)
    fibers = seed_fibers(capacity=8, n_active=3)
    # bind fiber 0 to site 0 so occupancy/rate bookkeeping is exercised
    bb = np.asarray(fibers.binding_body).copy()
    bs = np.asarray(fibers.binding_site).copy()
    bb[0], bs[0] = 0, 0
    fibers = device_group(fibers._replace(binding_body=bb, binding_site=bs))
    state = system.make_state(fibers=fibers, bodies=body_group)

    stats = {}
    host = apply_dynamic_instability(state, params, _SeqRNG(3), stats=stats)
    dev, info = di_device.di_update(
        state, params, jnp.asarray([0, 3, 0], jnp.int32),
        sample_fn=injected_sample_fn)
    hf, df = host.fibers, dev.fibers
    for name in ("active", "binding_body", "binding_site", "config_rank",
                 "minus_clamped", "plus_pinned"):
        np.testing.assert_array_equal(np.asarray(getattr(hf, name)),
                                      np.asarray(getattr(df, name)), name)
    for name in ("length", "length_prev", "v_growth", "bending_rigidity",
                 "radius", "penalty", "beta_tstep", "tension"):
        np.testing.assert_array_equal(np.asarray(getattr(hf, name)),
                                      np.asarray(getattr(df, name)), name)
    act = np.asarray(hf.active)
    np.testing.assert_allclose(np.asarray(df.x)[act], np.asarray(hf.x)[act],
                               rtol=1e-14, atol=1e-15)
    assert int(info.nucleations) == stats["nucleations"]
    assert int(info.catastrophes) == stats["catastrophes"]
    assert int(info.active_fibers) == act.sum()
    assert not bool(info.needs_growth)


def test_device_catastrophe_statistics():
    """Natural draws: the survival fraction over one step reproduces
    exp(-dt * f_cat) (the host oracle's statistical pin, device-side)."""
    params = di_params(di=dict(n_nodes=16, f_catastrophe=1.0,
                               nucleation_rate=0.0), dt_initial=0.05)
    system = System(params)
    nf = 2000
    x = np.tile(np.linspace(0, 1, 16)[None, :, None], (nf, 1, 3))
    fibers = device_group(fc.make_group(x, lengths=1.0,
                                        bending_rigidity=0.01,
                                        radius=0.0125))
    state = system.make_state(fibers=fibers)
    state = state._replace(dt=jnp.asarray(0.05, jnp.float64))
    _, info = di_device.di_update(
        state, params, jnp.asarray([0, 3, 0], jnp.int32))
    frac = float(info.active_fibers) / nf
    expected = np.exp(-0.05 * 1.0)
    assert frac == pytest.approx(expected, abs=3 * np.sqrt(expected / nf))


def test_needs_growth_aborts_update_bitwise(body_group):
    """A nucleation burst beyond the free slots aborts the WHOLE update:
    the state comes back bitwise identical and the info reports only the
    flag (the lane freeze + reseat contract)."""
    params = di_params(di=dict(n_nodes=8, f_catastrophe=0.0,
                               nucleation_rate=60.0))
    system = System(params)
    fibers = device_group(seed_fibers(capacity=2, n_active=2))
    state = system.make_state(fibers=fibers, bodies=body_group)

    def burst(di_rng, lam, capacity, n_sites, dtype):
        d = injected_sample_fn(di_rng, lam, capacity, n_sites, dtype)
        return d._replace(n_raw=jnp.int32(3), u_cat=jnp.zeros_like(d.u_cat))

    out, info = di_device.di_update(
        state, params, jnp.asarray([0, 3, 0], jnp.int32), sample_fn=burst)
    assert bool(info.needs_growth)
    assert int(info.nucleations) == 0 and int(info.catastrophes) == 0
    for name, leaf in zip(state.fibers._fields, state.fibers):
        if name == "rt_mats" or leaf is None:
            continue
        np.testing.assert_array_equal(np.asarray(leaf),
                                      np.asarray(getattr(out.fibers, name)),
                                      name)


def test_ensure_di_capacity_and_validation(body_group):
    params = di_params()
    system = System(params)
    # fiber-less scene: placeholder group seeded from the first site
    state = ensure_di_capacity(system.make_state(bodies=body_group), params)
    g = state.fibers
    assert isinstance(g, fc.FiberGroup) and g.n_fibers >= 1
    assert not np.asarray(g.active).any()
    assert np.isfinite(np.asarray(g.x)).all()
    # a resolution mismatch fails loudly at assembly
    bad = di_params(di=dict(n_nodes=16))
    with pytest.raises(ValueError, match="resolution"):
        di_device.check_di_state(state, bad)
    # mixed-resolution tuples are a host-loop-only configuration
    two = (seed_fibers(capacity=2), seed_fibers(capacity=2, n_nodes=16))
    with pytest.raises(ValueError, match="single"):
        ensure_di_capacity(
            system.make_state(fibers=two, bodies=body_group), params)


# ----------------------------------------------------- batched sweep pins

def _scenario_members(system, body_group, n, capacity=8, rng_pairs=None):
    members, hosts = [], {}
    for i in range(n):
        fibers = device_group(seed_fibers(capacity=capacity, n_active=2,
                                          shift=0.2 * i))
        state = system.make_state(fibers=fibers, bodies=body_group)
        dev_rng, host_rng = (rng_pairs[i] if rng_pairs
                             else member_rng_pair(i))
        members.append(MemberSpec(member_id=f"m{i}", state=state,
                                  t_final=system.params.t_final,
                                  rng=dev_rng))
        hosts[f"m{i}"] = (state, host_rng)
    return members, hosts


@pytest.mark.slow  # two compiled coupled programs (solo + vmap batch), ~1 min
def test_vmap_sweep_matches_host_loop_injected(body_group):
    """Ensemble-leg acceptance pin (free-space half): B=3 DI members on the
    vmap path, injected deterministic draws — per-member trajectories match
    three sequential host-loop `System.run` executions at the vmap-plan
    tolerance, and the scheduler's metrics carry the population
    trajectory."""
    params = di_params()
    system = System(params)
    members, hosts = _scenario_members(system, body_group, 3)

    seq = {}
    for mid, (state, host_rng) in hosts.items():
        frames = []
        system.run(state, rng=host_rng,
                   writer=lambda s, sol, **kw: frames.append(s))
        seq[mid] = frames

    runner = EnsembleRunner(system, di_sample_fn=injected_sample_fn)
    got = {m.member_id: [] for m in members}
    records = []
    se = ScenarioEnsemble(
        system, members, batch=3, runner=runner, metrics=records.append,
        writer=lambda mid, s, rng_state=None: got[mid].append(s))
    finished = se.run(max_rounds=50)
    assert sorted(finished) == sorted(got)
    assert se.reseats == 0

    for mid, frames in got.items():
        ref = seq[mid]
        assert len(ref) == len(frames) > 0, mid
        for k, (a, b) in enumerate(zip(ref, frames)):
            assert float(a.time) == float(b.time)
            np.testing.assert_array_equal(np.asarray(a.fibers.active),
                                          np.asarray(b.fibers.active),
                                          f"{mid} frame {k} active")
            np.testing.assert_array_equal(np.asarray(a.fibers.binding_site),
                                          np.asarray(b.fibers.binding_site))
            act = np.asarray(a.fibers.active)
            np.testing.assert_allclose(
                np.asarray(b.fibers.x)[act], np.asarray(a.fibers.x)[act],
                rtol=1e-9, atol=1e-12,
                err_msg=f"{mid} frame {k} positions")
            np.testing.assert_allclose(
                np.asarray(b.fibers.length), np.asarray(a.fibers.length),
                rtol=1e-12, atol=0)
    steps = [r for r in records if r.get("event") == "step"]
    assert sum(r["nucleations"] for r in steps) > 0
    assert all("active_fibers" in r for r in steps)


@pytest.fixture(scope="module")
def shell_pair():
    """(PeripheryState, PeripheryShape): a small confining sphere."""
    import jax

    from skellysim_tpu.periphery import periphery as peri
    from skellysim_tpu.periphery.precompute import precompute_periphery

    assert jax.config.jax_enable_x64
    data = precompute_periphery("sphere", n_nodes=60, radius=2.5, eta=1.0)
    state = peri.make_state(data["nodes"], data["normals"],
                            data["quadrature_weights"],
                            data["stresslet_plus_complementary"],
                            data["M_inv"], dtype=jnp.float64)
    return state, peri.PeripheryShape(kind="sphere", radius=2.5)


@pytest.mark.slow  # coupled periphery programs, solo + vmap (~2 min on CPU)
def test_confined_sweep_matches_host_loop(body_group, shell_pair):
    """THE oocyte-class acceptance pin (ROADMAP item 5, ensemble leg): a
    B-member CONFINED dynamic-instability sweep — periphery + nucleating
    body + growing/shrinking fibers — runs on the ensemble vmap path, and
    with injected deterministic draws each member's trajectory matches the
    sequential host-loop `System.run` at the vmap-plan tolerance."""
    shell, shape = shell_pair
    params = di_params(t_final=0.06)
    system = System(params, shell_shape=shape)

    B = 2
    members, hosts = [], {}
    for i in range(B):
        fibers = device_group(seed_fibers(capacity=8, n_active=2,
                                          shift=0.15 * i))
        # keep the seeded fibers inside the confining sphere
        fibers = fibers._replace(x=fibers.x * 0.4)
        state = system.make_state(fibers=fibers, bodies=body_group,
                                  shell=shell)
        dev_rng, host_rng = member_rng_pair(i)
        members.append(MemberSpec(member_id=f"m{i}", state=state,
                                  t_final=params.t_final, rng=dev_rng))
        hosts[f"m{i}"] = (state, host_rng)

    seq = {}
    for mid, (state, host_rng) in hosts.items():
        frames = []
        system.run(state, rng=host_rng,
                   writer=lambda s, sol, **kw: frames.append(s))
        seq[mid] = frames
        assert any(np.asarray(f.fibers.active).sum()
                   > np.asarray(state.fibers.active).sum()
                   for f in frames), "confined host run never nucleated"

    runner = EnsembleRunner(system, di_sample_fn=injected_sample_fn)
    got = {m.member_id: [] for m in members}
    se = ScenarioEnsemble(
        system, members, batch=B, runner=runner,
        writer=lambda mid, s, rng_state=None: got[mid].append(s))
    finished = se.run(max_rounds=40)
    assert sorted(finished) == sorted(got)

    for mid, frames in got.items():
        ref = seq[mid]
        assert len(ref) == len(frames) > 0, mid
        for k, (a, b) in enumerate(zip(ref, frames)):
            assert float(a.time) == float(b.time)
            np.testing.assert_array_equal(np.asarray(a.fibers.active),
                                          np.asarray(b.fibers.active))
            act = np.asarray(a.fibers.active)
            np.testing.assert_allclose(
                np.asarray(b.fibers.x)[act], np.asarray(a.fibers.x)[act],
                rtol=1e-9, atol=1e-12,
                err_msg=f"{mid} confined frame {k}")
            np.testing.assert_allclose(
                np.asarray(b.shell.density), np.asarray(a.shell.density),
                rtol=1e-8, atol=1e-11)


@pytest.mark.slow  # compiles one rung program per capacity (~2 min on CPU)
def test_growth_reseat_zero_compiles_one_trace_per_rung(body_group):
    """THE warm-program pin: within-bucket nucleation/catastrophe produce
    ZERO observed_jit compile events after a rung warms, and a capacity
    overflow reseats onto the next geometric rung with EXACTLY one new
    trace (trace_counting_jit over the shared batched step)."""
    params = di_params(di=dict(n_nodes=8, f_catastrophe=0.2,
                               nucleation_rate=80.0), t_final=0.08)
    system = System(params)
    members, _ = _scenario_members(system, body_group, 2, capacity=2)

    runner = EnsembleRunner(system)
    step = trace_counting_jit(runner.step_impl)
    tracer = obs_tracer.Tracer(None)
    records = []
    with obs_tracer.use(tracer):
        se = ScenarioEnsemble(system, members, batch=2, runner=runner,
                              step_fn=step, metrics=records.append)
        finished = se.run(max_rounds=60)
    assert sorted(finished) == ["m0", "m1"]
    assert se.reseats >= 1, "sweep never outgrew its 2-slot rung"
    rungs = sorted(se._scheds)
    # one trace per capacity rung, ever — reseats and later steps reuse them
    assert step.trace_count == len(rungs), (step.trace_count, rungs)
    growth_events = [e for e in tracer.events
                     if e.get("ev") == "lane" and e.get("action") == "growth"]
    assert growth_events, "no growth events surfaced in telemetry"
    # the fiber population grew in-trace (mask flips, not reshapes):
    # members seeded 2 live fibers, the recorded steps carry more
    steps = [r for r in records if r.get("event") == "step"]
    assert sum(r["nucleations"] for r in steps) >= 1
    assert max(r["active_fibers"] for r in steps) > 2


@pytest.mark.slow  # one vmap coupled compile (~40 s on CPU)
def test_di_failed_lane_quarantine(body_group):
    """Guard semantics under DI: a poisoned lane retires ``failed`` with a
    nonfinite verdict while its sibling finishes its whole trajectory."""
    from skellysim_tpu.guard import chaos, verdict

    params = di_params()
    system = System(params)
    members, _ = _scenario_members(system, body_group, 2)
    runner = EnsembleRunner(system)
    records = []
    sched = EnsembleScheduler(runner, members, 2, metrics=records.append,
                              on_failure="retire", on_growth="retire")
    sched.ens = chaos.poison_lane(sched.ens, sched.lane_of("m0"))
    retired = sched.run()
    fails = [r for r in records if r.get("event") == "failed"]
    assert [f["member"] for f in fails] == ["m0"]
    assert fails[0]["health"] & verdict.NONFINITE
    assert "m1" in retired
    m1_steps = [r for r in records
                if r.get("event") == "step" and r["member"] == "m1"]
    assert m1_steps and m1_steps[-1]["t"] + m1_steps[-1]["dt"] \
        >= params.t_final - 1e-12


def test_scheduler_requires_member_rng(body_group):
    params = di_params()
    system = System(params)
    members, _ = _scenario_members(system, body_group, 1)
    runner = EnsembleRunner(system)
    spec = dataclasses.replace(members[0], rng=None)
    with pytest.raises(ValueError, match="SimRNG"):
        EnsembleScheduler(runner, [spec], 1)
    with pytest.raises(ValueError, match="SimRNG"):
        ScenarioEnsemble(system, [spec], 1, runner=runner)


def test_summarize_renders_scenario_table():
    """`obs summarize` renders the dynamic-instability table from ensemble
    step records carrying the new fields."""
    import json

    from skellysim_tpu.obs.summarize import Summary

    s = Summary()
    base = {"event": "step", "lane": 0, "round": 0, "step": 0, "t": 0.0,
            "dt": 0.02, "iters": 3, "gmres_cycles": 1, "residual": 1e-11,
            "residual_true": 1e-11, "fiber_error": 0.0, "accepted": True,
            "refines": 0, "loss_of_accuracy": False, "health": 0,
            "guard_retries": 0, "wall_s": 0.1, "wall_ms": 100.0,
            "gmres_history": []}
    for step, (n, c, a) in enumerate([(2, 0, 4), (1, 1, 4), (0, 2, 2)]):
        s.add_line(json.dumps(dict(base, member="m0", step=step, round=step,
                                   nucleations=n, catastrophes=c,
                                   active_fibers=a)))
    s.add_line(json.dumps({"ev": "lane", "action": "growth", "lane": 0,
                           "member": "m0", "capacity": 4}))
    out = s.render()
    assert "dynamic instability" in out
    assert "nucleations=3" in out and "catastrophes=3" in out
    assert "growth-reseats=1" in out
    assert "4 -> 2, max 4" in out


def test_summarize_omits_scenario_table_without_di():
    import json

    from skellysim_tpu.obs.summarize import Summary

    s = Summary()
    s.add_line(json.dumps({"step": 0, "t": 0.0, "dt": 0.01, "iters": 4,
                           "accepted": True, "nucleations": 0,
                           "catastrophes": 0, "active_fibers": 0}))
    assert "dynamic instability" not in s.render()
