"""Worker for the 2-process jax.distributed smoke test.

Launched by `tests/test_multihost.py` as two subprocesses (the CI-runnable
counterpart of the reference's 2-rank mpiexec ctest tier,
`/root/reference/tests/core/unit_tests/CMakeLists.txt:12-19`): each process
owns 2 virtual CPU devices, joins the distributed runtime through
`parallel.multihost.initialize`, and drives one ring-evaluator Stokes sum
sharded over the GLOBAL 4-device mesh — collective-permutes cross the
process boundary. Prints "MULTIHOST-OK" on success.
"""

import sys

port, pid = sys.argv[1], int(sys.argv[2])

# platform pinning (JAX_PLATFORMS=cpu, 2 virtual devices) comes from the
# launching test's environment: jax.distributed.initialize must be the FIRST
# jax call in the process, so the in-process bootstrap helper (which probes
# jax.device_count) cannot be used here
from skellysim_tpu.parallel import multihost

assert multihost.initialize(f"localhost:{port}", 2, pid) is True

import jax

jax.config.update("jax_enable_x64", True)

import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from skellysim_tpu.parallel import make_mesh
from skellysim_tpu.parallel.ring import ring_stokeslet

info = multihost.process_info()
assert info["process_count"] == 2, info
assert info["local_device_count"] == 2, info
assert info["global_device_count"] == 4, info

mesh = make_mesh()
assert mesh.size == 4

rng = np.random.default_rng(0)
n = 16
r = rng.uniform(-1.0, 1.0, (n, 3))
f = rng.standard_normal((n, 3))
sharding = NamedSharding(mesh, P("fib"))


def ga(a):
    return jax.make_array_from_callback(a.shape, sharding,
                                        lambda idx: a[idx])


out = ring_stokeslet(ga(r), ga(r), ga(f), 1.3, mesh=mesh)
rep = jax.jit(lambda x: x, out_shardings=NamedSharding(mesh, P()))(out)
got = np.asarray(rep.addressable_data(0))

# plain-NumPy dense oracle (no device work): same masking semantics
d = r[:, None, :] - r[None, :, :]
r2 = (d * d).sum(-1)
np.fill_diagonal(r2, np.inf)
rinv = 1.0 / np.sqrt(r2)
df = np.einsum("tsk,sk->ts", d, f)
ref = (np.einsum("ts,sk->tk", rinv, f)
       + np.einsum("ts,tsk->tk", df * rinv**3, d)) / (8 * np.pi * 1.3)

err = np.linalg.norm(got - ref) / np.linalg.norm(ref)
assert err < 5e-9, err
print("MULTIHOST-OK", pid, flush=True)
