"""The getting-started walkthrough (docs/getting_started.md) runs as written.

Executes the documented command sequence — gen_config.py -> precompute ->
run -> resume -> read — through real subprocesses so the docs cannot drift
from the CLI surface (the reference's docs walkthrough has the same role,
`docs/source/getting_started.rst:42-118`).
"""

import os
import re
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DOC = os.path.join(REPO, "docs", "getting_started.md")

_ENV = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)


def _run(args, cwd):
    proc = subprocess.run([sys.executable] + args, cwd=cwd, env=_ENV,
                          capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, f"{args}: {proc.stderr[-2000:]}"
    return proc


@pytest.mark.slow
def test_walkthrough_commands(tmp_path):
    doc = open(DOC).read()

    # the gen_config.py listing from the doc, verbatim
    m = re.search(r"```python\n# gen_config\.py\n(.*?)```", doc, re.S)
    assert m, "docs/getting_started.md lost its gen_config.py listing"
    (tmp_path / "gen_config.py").write_text(m.group(1))

    _run(["gen_config.py"], cwd=tmp_path)
    assert (tmp_path / "skelly_config.toml").exists()

    _run(["-m", "skellysim_tpu.precompute", "skelly_config.toml"], cwd=tmp_path)
    assert (tmp_path / "body_precompute.npz").exists()
    assert (tmp_path / "periphery_precompute.npz").exists()

    _run(["-m", "skellysim_tpu", "--config-file=skelly_config.toml"],
         cwd=tmp_path)
    assert (tmp_path / "skelly_sim.out").exists()
    assert (tmp_path / "skelly_sim.final_config").exists()

    # resume appends more frames (the trajectory is the checkpoint)
    from skellysim_tpu.io.trajectory import TrajectoryReader

    n_before = len(TrajectoryReader(str(tmp_path / "skelly_sim.out")))
    cfg = (tmp_path / "skelly_config.toml").read_text()
    (tmp_path / "skelly_config.toml").write_text(
        cfg.replace("t_final = 0.4", "t_final = 0.8"))
    _run(["-m", "skellysim_tpu", "--config-file=skelly_config.toml",
          "--resume"], cwd=tmp_path)

    traj = TrajectoryReader(str(tmp_path / "skelly_sim.out"))
    assert len(traj) > n_before
    frame = traj.load_frame(-1)
    # the documented reader access patterns
    x_last = np.asarray(traj["fibers"][0]["x_"])
    assert x_last.shape == (16, 3)
    body_pos = np.asarray(traj["bodies"][0]["position_"])
    assert body_pos.shape == (3,)
    # the body moved up under its constant +z force
    assert body_pos[2] > 0.0
    assert frame["time"] >= 0.4
