"""Trajectory interop proof using the REFERENCE's own reader as the oracle.

Imports `/root/reference/src/skelly_sim/reader.py` (pure Python, read-only)
and lets its `TrajectoryReader` read a trajectory written by OUR
`TrajectoryWriter` — the definitive byte-compatibility check (VERDICT r4 #7),
replacing re-stated schema expectations with the reference's actual decode
path (`reader.py:198-355`).

The reference module tree needs four tiny import shims for packages absent
from this image (`toml`, `dataclass_utils`, `nptyping`,
`function_generator`); they only satisfy module-level imports — all decode
logic that runs is the reference's own.
"""

import os
import sys
import types

try:
    import tomllib  # Python >= 3.11
except ModuleNotFoundError:
    import tomli as tomllib  # API-compatible backport (3.10 boxes)

import numpy as np
import pytest

import jax.numpy as jnp

from skellysim_tpu.bodies import bodies as bd
from skellysim_tpu.fibers import container as fc
from skellysim_tpu.io import TrajectoryWriter
from skellysim_tpu.params import Params
from skellysim_tpu.periphery import periphery as peri
from skellysim_tpu.system import System

REF_SRC = "/root/reference/src"

_STUBS = ("toml", "dataclass_utils", "nptyping", "function_generator")


@pytest.fixture()
def ref_reader_module():
    """Import the reference's `skelly_sim.reader` with dependency shims,
    cleaning all of it out of `sys.modules` afterwards."""
    if not os.path.isdir(REF_SRC):
        pytest.skip(f"reference checkout not present at {REF_SRC}")
    saved = {name: sys.modules.get(name)
             for name in _STUBS + ("skelly_sim",)}

    toml_stub = types.ModuleType("toml")
    toml_stub.load = lambda f: tomllib.loads(f.read())

    du_stub = types.ModuleType("dataclass_utils")
    du_stub.check_type = lambda *a, **k: None

    class _Subscriptable:
        def __class_getitem__(cls, item):
            return np.ndarray

    npt_stub = types.ModuleType("nptyping")
    npt_stub.NDArray = _Subscriptable
    npt_stub.Shape = _Subscriptable
    npt_stub.Float64 = float

    fg_stub = types.ModuleType("function_generator")
    fg_stub.FunctionGenerator = type("FunctionGenerator", (), {})

    sys.modules.update({"toml": toml_stub, "dataclass_utils": du_stub,
                        "nptyping": npt_stub, "function_generator": fg_stub})
    sys.path.insert(0, REF_SRC)
    try:
        import skelly_sim.reader as ref_reader  # noqa: PLC0415
        yield ref_reader
    finally:
        sys.path.remove(REF_SRC)
        for name in list(sys.modules):
            if name == "skelly_sim" or name.startswith("skelly_sim."):
                del sys.modules[name]
        for name, mod in saved.items():
            if mod is None:
                sys.modules.pop(name, None)
            else:
                sys.modules[name] = mod


def _mixed_state():
    """Fibers in two resolution buckets + shell + sphere/ellipsoid bodies —
    the full wire surface, bucket-ordered internally but config-ordered on
    the wire."""
    rng = np.random.default_rng(7)
    params = Params(eta=1.0, dt_initial=5e-3, t_final=1e-2, gmres_tol=1e-10,
                    adaptive_timestep_flag=False)
    system = System(params, shell_shape=peri.PeripheryShape(kind="generic"))

    def fibgroup(nf, n, rank0):
        x = np.cumsum(rng.standard_normal((nf, n, 3)) * 0.05, axis=1)
        g = fc.make_group(x, lengths=1.0, bending_rigidity=0.01, radius=0.0125)
        return g._replace(
            tension=jnp.asarray(rng.standard_normal((nf, n))),
            config_rank=jnp.arange(rank0, rank0 + nf))

    fibers = (fibgroup(2, 16, 0), fibgroup(3, 24, 2))

    def bodygroup(n_nodes, kind, rank0, nb):
        nodes = rng.standard_normal((n_nodes, 3))
        nodes /= np.linalg.norm(nodes, axis=1, keepdims=True)
        g = bd.make_group(
            np.broadcast_to(nodes[None], (nb, n_nodes, 3)),
            nodes, np.full(n_nodes, 4 * np.pi / n_nodes),
            position=rng.standard_normal((nb, 3)),
            radius=np.full(nb, 1.0), kind=kind)
        return g._replace(config_rank=jnp.arange(rank0, rank0 + nb))

    bodies = (bodygroup(32, "sphere", 0, 1),
              bodygroup(48, "ellipsoid", 1, 2))

    n_shell = 20
    shell_nodes = rng.standard_normal((n_shell, 3))
    shell_nodes /= np.linalg.norm(shell_nodes, axis=1, keepdims=True)
    eye = jnp.eye(3 * n_shell)
    shell = peri.make_state(shell_nodes, -shell_nodes,
                            np.full(n_shell, 4 * np.pi / n_shell), eye, eye)
    shell = shell._replace(
        density=jnp.asarray(rng.standard_normal(3 * n_shell)))

    state = system.make_state(fibers=fibers, shell=shell, bodies=bodies)
    return system, state


def test_reference_reader_reads_our_trajectory(tmp_path, ref_reader_module):
    toml_file = tmp_path / "skelly_config.toml"
    toml_file.write_text('[params]\neta = 1.0\ndt_initial = 5e-3\n')
    path = str(tmp_path / "skelly_sim.out")

    system, state = _mixed_state()
    rng_state = [["main", "0:1:2"]]
    with TrajectoryWriter(path) as tw:
        tw.write_frame(state, rng_state=rng_state)
        tw.write_frame(state._replace(time=state.time + state.dt))

    tr = ref_reader_module.TrajectoryReader(str(toml_file))
    assert tr.trajectory_version == 1
    assert tr.fiber_type == 1          # FIBER_TYPE_FINITE_DIFFERENCE
    assert len(tr) == 2
    assert tr.times == pytest.approx([0.0, 5e-3])

    tr.load_frame(0)
    assert set(tr.keys()) >= {"time", "dt", "rng_state", "fibers", "bodies",
                              "shell"}
    assert tr["time"] == pytest.approx(0.0)
    assert tr["dt"] == pytest.approx(5e-3)
    assert tr["rng_state"] == rng_state

    # fibers come back in config order, bucket-merged, through the
    # reference's __eigen__ decode (points along rows)
    fibs = tr["fibers"]
    assert len(fibs) == 5
    expect = [(0, 0), (0, 1), (1, 0), (1, 1), (1, 2)]  # (bucket, slot)
    for cfg_rank, (b, i) in enumerate(expect):
        g = state.fibers[b]
        assert fibs[cfg_rank]["n_nodes_"] == g.x.shape[1]
        np.testing.assert_array_equal(fibs[cfg_rank]["x_"],
                                      np.asarray(g.x[i], dtype=np.float64))
        np.testing.assert_array_equal(
            fibs[cfg_rank]["tension_"],
            np.asarray(g.tension[i], dtype=np.float64))
        assert fibs[cfg_rank]["minus_clamped_"] == bool(g.minus_clamped[i])

    # bodies flatten [spheres, deformable, ellipsoids] in the reference's
    # __getitem__; config order survives within each kind list
    bods = tr["bodies"]
    assert len(bods) == 3
    np.testing.assert_array_equal(
        bods[0]["position_"],
        np.asarray(state.bodies[0].position[0], dtype=np.float64))
    for j in range(2):
        np.testing.assert_array_equal(
            bods[1 + j]["position_"],
            np.asarray(state.bodies[1].position[j], dtype=np.float64))
        assert bods[1 + j]["orientation_"].shape == (4,)

    np.testing.assert_array_equal(
        tr["shell"]["solution_vec_"],
        np.asarray(state.shell.density, dtype=np.float64))

    # second frame via the reference's index path
    tr.load_frame(1)
    assert tr["time"] == pytest.approx(5e-3)


def test_reference_reader_uses_our_cindex(tmp_path, ref_reader_module):
    """Our native `.cindex` side file is accepted verbatim by the reference
    reader (same {mtime, offsets, times} schema, `reader.py:293-329`) —
    it must NOT fall back to a rebuild."""
    from skellysim_tpu.io import TrajectoryReader as OurReader

    toml_file = tmp_path / "skelly_config.toml"
    toml_file.write_text('[params]\neta = 1.0\n')
    path = str(tmp_path / "skelly_sim.out")

    system, state = _mixed_state()
    with TrajectoryWriter(path) as tw:
        for k in range(3):
            tw.write_frame(state._replace(time=state.time + k * state.dt))

    ours = OurReader(path)           # builds + persists the .cindex
    our_index = (tmp_path / "skelly_sim.out.cindex").read_bytes()
    assert len(ours) == 3

    tr = ref_reader_module.TrajectoryReader(str(toml_file))
    assert len(tr) == 3
    assert tr.times == pytest.approx(ours.times)
    # byte-identical index => the reference reader reused ours, not rebuilt
    assert (tmp_path / "skelly_sim.out.cindex").read_bytes() == our_index
    tr.load_frame(2)
    assert tr["time"] == pytest.approx(2 * float(state.dt))
