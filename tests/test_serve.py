"""skelly-serve: persistent multi-tenant service over warm ensemble lanes.

Pins the ISSUE-7 acceptance criteria and the serve subsystem's contracts:

* wire protocol round-trips for EVERY request type + incremental framing
  (one source of truth shared with `listener.py`);
* THE acceptance pin: two concurrent tenants with different configs in the
  same capacity bucket produce trajectories BITWISE matching their
  sequential `System.run` outputs, with zero ``compile`` events after
  warmup (`observed_jit` events through the server's StatsTracer — the
  `test_retrace.py` discipline at the service level);
* admission control: params-contract and capacity-bucket rejections, queue
  depth shedding, queued -> backfill promotion;
* mid-service snapshot/resume: evict a tenant, re-admit from its snapshot,
  combined trajectory bitwise-matches an uninterrupted run;
* `queue_wait_s` admission latency on lane events + `obs summarize`
  reporting it;
* the scheduler's incremental `admit`/`poll`/`evict` API on an
  initially-empty (template-constructed) service.
"""

import io
import json

import numpy as np
import pytest

from skellysim_tpu.builder import build_simulation
from skellysim_tpu.config import Body, BackgroundSource, Config, Fiber, schema
from skellysim_tpu.config.toml_io import dumps as toml_dumps
from skellysim_tpu.io.trajectory import frame_bytes
from skellysim_tpu.serve import protocol
from skellysim_tpu.serve.server import SimulationServer


def _tenant_cfg(shift=0.0, n_nodes=8, n_fibers=1, **param_overrides) -> Config:
    """Tiny free-fiber scene (fast-tier sized, like test_ensemble's)."""
    cfg = Config()
    cfg.params.eta = 1.0
    cfg.params.dt_initial = 0.005
    cfg.params.dt_write = 0.005
    cfg.params.t_final = 0.02
    cfg.params.gmres_tol = 1e-10
    cfg.params.adaptive_timestep_flag = False
    for k, v in param_overrides.items():
        setattr(cfg.params, k, v)
    fibers = []
    for i in range(n_fibers):
        fib = Fiber(n_nodes=n_nodes, length=1.0, bending_rigidity=0.01)
        fib.fill_node_positions(np.array([shift + 0.4 * i, 0.0, 0.0]),
                                np.array([0.0, 0.0, 1.0]))
        fibers.append(fib)
    cfg.fibers = fibers
    cfg.background = BackgroundSource(uniform=[1.0, 0.0, 0.0])
    return cfg


def _toml(cfg: Config) -> str:
    return toml_dumps(schema.unpack(cfg))


def _sequential_frames(cfg: Config) -> list:
    """Reference trajectory: initial frame + System.run boundary frames,
    with the rng_state stamp a CLI-written trajectory carries (serve frames
    carry it too, for resume continuity through `--resume`)."""
    system, state, rng = build_simulation(cfg)
    rs = rng.dump_state() if rng is not None else None
    frames = [frame_bytes(state, rng_state=rs)]
    system.run(state, writer=lambda st, sol, **kw: frames.append(
        frame_bytes(st, rng_state=rs)))
    return frames


@pytest.fixture(scope="module")
def server():
    """One warm 2-lane unroll server shared module-wide (tenant records
    accumulate; each test uses fresh tenant ids)."""
    return SimulationServer(
        _tenant_cfg(), serve_cfg=schema.ServeConfig(max_lanes=2,
                                                    batch_impl="unroll"))


def _submit(server, cfg, **fields):
    resp = server.handle_request({"type": "submit", "config": _toml(cfg),
                                  **fields})
    assert resp["ok"], resp.get("error")
    return resp


def _drain(server, max_rounds=200):
    n = 0
    while server.any_live() and n < max_rounds:
        server.tick()
        n += 1
    assert not server.any_live(), "service did not drain"


def _stream(server, tenant) -> list:
    resp = server.handle_request({"type": "stream", "tenant": tenant})
    assert resp["ok"]
    return [bytes(f) for f in resp["frames"]]


# ------------------------------------------------------------ wire protocol

def test_protocol_roundtrip_every_request_type():
    """Every request type survives make_request -> frame -> decode, through
    the same framing `listener.py` serves over."""
    samples = {
        "submit": dict(config="[params]\n", tenant="t1", t_final=0.5,
                       resume_frame=b"\x81\xa1x\x01"),
        "status": dict(tenant="t1"),
        "stream": dict(tenant="t1", max_frames=3),
        "snapshot": dict(tenant="t1"),
        "cancel": dict(tenant="t1"),
        "stats": {},
        "chaos": dict(action="nan_lane", tenant="t1"),
        "shutdown": {},
    }
    assert set(samples) == set(protocol.REQUEST_FIELDS)
    for rtype, fields in samples.items():
        req = protocol.make_request(rtype, **fields)
        buf = io.BytesIO()
        protocol.write_message(buf, req)
        buf.seek(0)
        back = protocol.read_message(buf)
        assert back == req, rtype
        assert protocol.validate_request(back) is None


def test_protocol_framing_edges():
    # zero-length control frame round-trips distinctly from EOF
    buf = io.BytesIO()
    protocol.write_empty(buf)
    buf.seek(0)
    assert protocol.read_frame(buf) == b""
    assert protocol.read_frame(buf) is None  # EOF
    # truncated payload = disconnect, not an exception
    buf = io.BytesIO(protocol.HEADER.pack(10) + b"abc")
    assert protocol.read_frame(buf) is None


def test_frame_decoder_incremental():
    """Byte-at-a-time feeding reassembles exactly the sent frames (the
    non-blocking socket path)."""
    msgs = [{"type": "stats"}, {"type": "status", "tenant": "t9"}]
    wire = b"".join(
        protocol.HEADER.pack(len(p)) + p
        for p in [protocol.pack_message(m) for m in msgs]) \
        + protocol.HEADER.pack(0)
    dec = protocol.FrameDecoder()
    out = []
    for i in range(len(wire)):
        out.extend(dec.feed(wire[i:i + 1]))
    assert [protocol.unpack_message(p) for p in out[:2]] == msgs
    assert out[2] == b""


def test_validate_request_rejections():
    assert "unknown request type" in protocol.validate_request({"type": "x"})
    assert "missing required" in protocol.validate_request({"type": "status"})
    assert "unknown field" in protocol.validate_request(
        {"type": "stats", "bogus": 1})
    assert "msgpack map" in protocol.validate_request([1, 2])


def test_serve_config_loading(tmp_path):
    p = tmp_path / "cfg.toml"
    p.write_text("[serve]\nmax_lanes = 3\nbucket_capacities = [2, 4]\n"
                 "queue_depth = 5\n")
    sc = schema.load_serve_config(str(p))
    assert (sc.max_lanes, sc.bucket_capacities, sc.queue_depth) == (3, [2, 4], 5)
    p.write_text("[serve]\nmax_lens = 3\n")
    with pytest.raises(ValueError, match="unknown \\[serve\\] keys"):
        schema.load_serve_config(str(p))
    p.write_text("[serve]\nbatch_impl = 'nope'\n")
    with pytest.raises(ValueError, match="batch_impl"):
        schema.load_serve_config(str(p))


# --------------------------------------------------- the acceptance criteria

def test_two_tenants_bitwise_parity_zero_compiles_after_warm(server):
    """THE acceptance pin: two concurrent tenants with different configs in
    the same capacity bucket; per-tenant frame streams BITWISE identical to
    their sequential System.run trajectories; zero compile events after
    warmup (observed_jit events through the server tracer)."""
    assert server.metrics.warm and server.metrics.compiles >= 1
    compiles_at_warm = server.metrics.compiles

    shifts = (0.1, 0.3)
    resp = [_submit(server, _tenant_cfg(s)) for s in shifts]
    assert [r["lane"] for r in resp] == [0, 1]  # concurrent, same bucket
    assert len({r["bucket"] for r in resp}) == 1
    _drain(server)

    for r, s in zip(resp, shifts):
        got = _stream(server, r["tenant"])
        assert len(got) >= 3
        assert got == _sequential_frames(_tenant_cfg(s))
        st = server.handle_request({"type": "status", "tenant": r["tenant"]})
        assert st["status"] == "finished" and st["t"] <= st["t_final"]

    assert server.metrics.compiles == compiles_at_warm
    assert server.metrics.stats()["compiles_after_warm"] == 0


def test_snapshot_evict_resume_matches_uninterrupted(server):
    """Satellite pin: evict a tenant mid-service, re-admit from its
    snapshot — pre-eviction + post-resume frames bitwise-match an
    uninterrupted run's."""
    cfg = _tenant_cfg(0.7)
    r = _submit(server, cfg)
    server.tick()
    server.tick()
    snap = server.handle_request({"type": "snapshot", "tenant": r["tenant"]})
    assert snap["ok"] and snap["status"] == "running"
    # graceful eviction (the disconnect path drives the same _release)
    server.handle_request({"type": "cancel", "tenant": r["tenant"]})
    pre = _stream(server, r["tenant"])
    st = server.handle_request({"type": "status", "tenant": r["tenant"]})
    assert st["status"] == "cancelled"

    r2 = server.handle_request({
        "type": "submit", "config": _toml(cfg),
        "resume_frame": bytes(snap["frame"])})
    assert r2["ok"], r2.get("error")
    _drain(server)
    post = _stream(server, r2["tenant"])
    assert pre + post == _sequential_frames(cfg)
    assert server.metrics.stats()["compiles_after_warm"] == 0


def test_disconnect_evicts_and_snapshot_survives(server):
    """A client disconnect gracefully evicts its tenants: lane freed, final
    snapshot retained for a later resume."""
    conn = object()
    r = _submit(server, _tenant_cfg(0.9))
    # hand ownership to a fake connection, then drop it
    server.registry.get(r["tenant"]).conn = conn
    server.tick()
    server.evict_conn(conn)
    st = server.handle_request({"type": "status", "tenant": r["tenant"]})
    assert st["status"] == "evicted" and st["lane"] is None
    snap = server.handle_request({"type": "snapshot", "tenant": r["tenant"]})
    assert snap["ok"] and snap["t"] > 0.0
    assert not server.any_live()


# ----------------------------------------------------------- admission rules

def test_params_contract_rejection(server):
    resp = server.handle_request({
        "type": "submit", "config": _toml(_tenant_cfg(gmres_tol=1e-6))})
    assert not resp["ok"] and "gmres_tol" in resp["error"]
    resp = server.handle_request({
        "type": "submit",
        "config": _toml(_tenant_cfg(0.1, t_final=0.01, seed=7)),
        "t_final": 0.01})
    # seed/t_final are the per-tenant exceptions — this one must admit
    assert resp["ok"], resp.get("error")
    _drain(server)


def test_bucket_mismatch_rejection(server):
    resp = server.handle_request({
        "type": "submit", "config": _toml(_tenant_cfg(n_nodes=16))})
    assert not resp["ok"] and "bucket" in resp["error"]
    resp = server.handle_request({
        "type": "submit", "config": _toml(_tenant_cfg(n_fibers=3))})
    assert not resp["ok"]
    assert server.metrics.rejected >= 2


def test_tenant_config_validation(server):
    for bad, needle in [
        ("not toml [", "parse error"),
        ("[params]\nt_final = 0.02\n", "no fibers"),
    ]:
        resp = server.handle_request({"type": "submit", "config": bad})
        assert not resp["ok"] and needle in resp["error"]


def test_queue_depth_sheds_and_backfills(server):
    """Admission control: lanes full -> queue; queue full -> structured
    rejection with retry=True; drained lanes backfill from the queue."""
    rs = [_submit(server, _tenant_cfg(0.05 * i)) for i in range(3)]
    assert rs[2]["queued"] and rs[2]["lane"] is None
    st = server.handle_request({"type": "status", "tenant": rs[2]["tenant"]})
    assert st["status"] == "queued"

    depth = server.serve_cfg.queue_depth
    server.serve_cfg.queue_depth = 1  # one slot, already taken by rs[2]
    try:
        resp = server.handle_request({
            "type": "submit", "config": _toml(_tenant_cfg(0.9))})
        assert not resp["ok"] and resp.get("retry") is True
    finally:
        server.serve_cfg.queue_depth = depth

    _drain(server)
    for r in rs:
        st = server.handle_request({"type": "status", "tenant": r["tenant"]})
        assert st["status"] == "finished"
        assert len(_stream(server, r["tenant"])) >= 3


def test_cancel_queued_tenant(server):
    rs = [_submit(server, _tenant_cfg(0.05 * i)) for i in range(3)]
    assert rs[2]["queued"]
    resp = server.handle_request({"type": "cancel",
                                  "tenant": rs[2]["tenant"]})
    assert resp["ok"] and resp["status"] == "cancelled"
    # releasing a QUEUED tenant keeps its spec state as the snapshot — a
    # resumed submit buffers no initial frame, so dropping the spec
    # without this would lose the tenant's resume point entirely
    snap = server.handle_request({"type": "snapshot",
                                  "tenant": rs[2]["tenant"]})
    assert snap["ok"] and snap["t"] == 0.0
    _drain(server)
    done = [server.handle_request({"type": "status", "tenant": r["tenant"]})
            ["status"] for r in rs]
    assert done == ["finished", "finished", "cancelled"]


def test_record_ttl_expires_terminal_records(server):
    """`[serve] record_ttl_s`: terminal tenant records expire that long
    after retirement (bounded retention — docs/serving.md); live tenants
    and records inside the TTL survive; 0 (the default) disables expiry."""
    r = _submit(server, _tenant_cfg(0.55), t_final=0.0)
    _drain(server)
    tid = r["tenant"]
    assert server.handle_request({"type": "status", "tenant": tid})["ok"]
    old_ttl = server.serve_cfg.record_ttl_s
    try:
        server.serve_cfg.record_ttl_s = 60.0
        server.tick()                      # inside the TTL: record survives
        assert server.handle_request({"type": "status", "tenant": tid})["ok"]
        # age the record past the TTL instead of sleeping (fast tier)
        server.registry.get(tid).retired_at -= 120.0
        resp = server.handle_request({"type": "status", "tenant": tid})
        assert not resp["ok"] and "unknown tenant" in resp["error"]
        # a live (running/queued) tenant has no retirement clock at all
        r2 = _submit(server, _tenant_cfg(0.6))
        assert server.registry.get(r2["tenant"]).retired_at is None
        _drain(server)
        assert server.handle_request(
            {"type": "status", "tenant": r2["tenant"]})["ok"]
    finally:
        server.serve_cfg.record_ttl_s = old_ttl


def test_explicit_zero_t_final(server):
    """A requested t_final of 0.0 is honored (no falsy substitution of the
    config's): the tenant admits and retires without stepping."""
    r = _submit(server, _tenant_cfg(0.4), t_final=0.0)
    _drain(server)
    st = server.handle_request({"type": "status", "tenant": r["tenant"]})
    assert st["status"] == "finished" and st["steps"] == 0


def test_stats_shape_and_stream_accounting(server):
    stats = server.handle_request({"type": "stats"})["stats"]
    for key in ("admitted", "rejected", "retired", "retire_reasons",
                "rounds", "steps", "steps_per_s", "mean_occupancy",
                "admission_wait_s", "compiles", "compiles_after_warm",
                "warm", "frames_streamed", "frames_streamed_total",
                "tenants", "buckets",
                # skelly-pulse SLO histograms (docs/serving.md)
                "round_wall_s_hist", "frame_stream_s", "histograms"):
        assert key in stats, key
    assert stats["warm"] is True
    assert stats["buckets"][0]["lanes"] == 2
    assert stats["frames_streamed_total"] >= 3
    assert stats["admission_wait_s"]["n"] == stats["admitted"]
    # percentile read-out from the folded events, ordered as percentiles
    for key in ("admission_wait_s", "round_wall_s_hist", "frame_stream_s"):
        slo = stats[key]
        assert slo["p50"] <= slo["p95"] <= slo["p99"], (key, slo)
    assert stats["round_wall_s_hist"]["n"] == stats["rounds"] > 0
    assert stats["frame_stream_s"]["n"] >= 1
    # the prometheus text page renders from the same payload
    from skellysim_tpu.serve import protocol

    prom = protocol.render_prometheus(stats)
    assert "skellysim_serve_round_wall_seconds_bucket" in prom
    assert 'le="+Inf"' in prom
    assert prom.strip().splitlines()[-1].startswith(
        "skellysim_serve_frame_stream_seconds_count")


def test_unknown_tenant_and_malformed_requests(server):
    assert "unknown tenant" in server.handle_request(
        {"type": "status", "tenant": "nope"})["error"]
    assert "unknown request type" in server.handle_request(
        {"type": "gibberish"})["error"]


# ------------------------------------------------- queue_wait_s + summarize

def test_queue_wait_on_lane_events_and_summarize(server):
    """Lane admit/backfill events carry queue_wait_s (admission latency);
    `obs summarize` folds them into the lane table."""
    lane_events = [e for e in server.tracer.events if e["ev"] == "lane"
                   and e["action"] in ("admit", "backfill")]
    assert lane_events, "no lane admissions recorded"
    assert all("queue_wait_s" in e and e["queue_wait_s"] >= 0.0
               for e in lane_events)
    # a queued tenant (lanes were busy) must show a strictly positive wait
    assert any(e["queue_wait_s"] > 0.0 for e in lane_events
               if e["action"] == "backfill")

    from skellysim_tpu.obs.summarize import Summary

    s = Summary()
    for e in server.tracer.events:
        s.add_line(json.dumps(e))
    report = s.render()
    assert "admission wait:" in report
    assert "ensemble lanes" in report


# -------------------------------------------- scheduler incremental service

def test_scheduler_template_admit_poll_evict():
    """The incremental API directly: an initially-EMPTY scheduler built
    from a template, members admitted/evicted between polls, one trace."""
    from skellysim_tpu.ensemble import EnsembleRunner, EnsembleScheduler
    from skellysim_tpu.ensemble.scheduler import MemberSpec
    from skellysim_tpu.testing import trace_counting_jit

    system, state, _ = build_simulation(_tenant_cfg())
    runner = EnsembleRunner(system)
    step = trace_counting_jit(runner.step_impl)
    sched = EnsembleScheduler(runner, [], 2, template=state, step_fn=step)
    assert sched.poll() == [] and sched.rounds == 0  # idle no-op

    lane = sched.admit(MemberSpec(member_id="a", state=state, t_final=0.02))
    assert lane == 0 and sched.live == 1
    sched.poll()
    assert sched.admit(MemberSpec(member_id="b", state=_tenant_state(0.2),
                                  t_final=0.02)) == 1
    mid = sched.evict(0, reason="evicted")
    assert float(mid.time) > 0.0 and sched.lane_of("a") is None
    # evicted lane state resumes exactly: re-admit and drain both
    assert sched.admit(MemberSpec(member_id="a2", state=mid,
                                  t_final=0.02)) == 0
    sched.run()
    assert set(sched.retired) == {"a", "b", "a2"}
    assert step.trace_count == 1, "incremental service retraced"


def _tenant_state(shift):
    _, state, _ = build_simulation(_tenant_cfg(shift))
    return state


# --------------------------------------------------------- padded admission

@pytest.mark.slow  # second compiled bucket program (own capacity)
def test_padded_bucket_admission_parity():
    """A 1-fiber tenant admits into a capacity-2 bucket (inert masked
    padding); its streamed trajectory matches the unpadded sequential run
    to roundoff, and frames carry only the ACTIVE fibers."""
    srv = SimulationServer(
        _tenant_cfg(), serve_cfg=schema.ServeConfig(
            max_lanes=2, bucket_capacities=[2], batch_impl="unroll"))
    cfg = _tenant_cfg(0.2)
    r = _submit(srv, cfg)
    assert r["bucket"] == 2
    _drain(srv)
    got = _stream(srv, r["tenant"])
    seq = _sequential_frames(cfg)
    assert len(got) == len(seq)
    for gb, sb in zip(got, seq):
        g = protocol.unpack_message(gb)
        s = protocol.unpack_message(sb)
        assert len(g["fibers"][1]) == 1  # active fibers only on the wire
        np.testing.assert_allclose(
            np.asarray(g["fibers"][1][0]["x_"]),
            np.asarray(s["fibers"][1][0]["x_"]), rtol=0, atol=1e-10)
    assert srv.metrics.stats()["compiles_after_warm"] == 0


# ------------------------------------------------------------ socket + CLI

# ------------------------------------------------ skelly-guard robustness

def test_frame_decoder_oversized_header_survives():
    """A header past the bound yields the OversizedFrame sentinel
    IMMEDIATELY, the declared bytes are skipped as they arrive, and
    framing resynchronizes on the next real frame — byte-at-a-time."""
    dec = protocol.FrameDecoder(max_frame_bytes=64)
    payload = protocol.pack_message({"type": "stats"})
    wire = (protocol.HEADER.pack(100) + b"x" * 100
            + protocol.HEADER.pack(len(payload)) + payload)
    out = []
    for i in range(len(wire)):
        out.extend(dec.feed(wire[i:i + 1]))
    assert isinstance(out[0], protocol.OversizedFrame)
    assert out[0].size == 100
    assert protocol.unpack_message(out[1]) == {"type": "stats"}
    assert dec.oversized == 1


def test_frame_decoder_boundary_sizes():
    """Exactly-at-bound frames pass; one byte over trips the sentinel."""
    dec = protocol.FrameDecoder(max_frame_bytes=32)
    exact = b"a" * 32
    assert dec.feed(protocol.HEADER.pack(32) + exact) == [exact]
    out = dec.feed(protocol.HEADER.pack(33) + b"b" * 33)
    assert len(out) == 1 and isinstance(out[0], protocol.OversizedFrame)
    # after the skip the decoder is clean again
    assert dec.feed(protocol.HEADER.pack(32) + exact) == [exact]


def test_frame_decoder_truncated_then_completed():
    dec = protocol.FrameDecoder()
    payload = protocol.pack_message({"type": "stats"})
    framed = protocol.HEADER.pack(len(payload)) + payload
    assert dec.feed(framed[:5]) == []
    assert dec.feed(framed[5:]) == [payload]


def test_frame_decoder_garbage_stream_does_not_raise():
    """Random bytes whose fake header claims an absurd size park the
    decoder in skip mode (framing cannot resync inside garbage) — but
    never raise: the server answers an error and stays up."""
    from skellysim_tpu.guard import chaos as chaos_mod

    dec = protocol.FrameDecoder()
    garbage = chaos_mod.garble_frame(bytes(64), seed=7, flips=64)
    out = dec.feed(garbage)
    assert all(isinstance(f, (bytes, protocol.OversizedFrame))
               for f in out)


class _StubConn:
    """Scripted socket for `_service_conn` (recv once, capture sends)."""

    def __init__(self, data: bytes):
        self._data = data
        self.sent = b""

    def recv(self, n):
        d, self._data = self._data, b""
        return d

    def sendall(self, b):
        self.sent += b

    def close(self):
        pass


class _StubSel:
    def unregister(self, c):
        pass


def _served_responses(server, wire: bytes, max_frame_bytes=None):
    conn = _StubConn(wire)
    dec = (protocol.FrameDecoder(max_frame_bytes=max_frame_bytes)
           if max_frame_bytes else protocol.FrameDecoder())
    decoders = {conn: dec}
    server._service_conn(conn, decoders, _StubSel())
    out = protocol.FrameDecoder().feed(conn.sent)
    return conn, decoders, [protocol.unpack_message(f) for f in out]


def test_server_survives_garbled_frame(server):
    """Satellite pin: a well-framed but undecodable request answers a
    structured error and the connection survives."""
    from skellysim_tpu.guard import chaos as chaos_mod

    garbled = chaos_mod.garble_frame(
        protocol.pack_message({"type": "stats"}), seed=1)
    wire = protocol.HEADER.pack(len(garbled)) + garbled
    conn, decoders, resps = _served_responses(server, wire)
    assert resps and resps[0]["ok"] is False
    assert "undecodable" in resps[0]["error"]
    assert conn in decoders  # NOT dropped
    # and a valid request on the same (surviving) connection still works
    valid = protocol.pack_message({"type": "stats"})
    conn2 = _StubConn(protocol.HEADER.pack(len(valid)) + valid)
    decoders[conn2] = decoders.pop(conn)
    server._service_conn(conn2, decoders, _StubSel())
    ok = protocol.unpack_message(protocol.FrameDecoder().feed(conn2.sent)[0])
    assert ok["ok"] is True


def test_server_survives_oversized_frame(server):
    """Satellite pin: an oversized header answers a structured error
    (flagged ``oversized``) without waiting for the body, and the
    connection survives."""
    wire = protocol.HEADER.pack(1 << 40)
    conn, decoders, resps = _served_responses(server, wire)
    assert resps and resps[0]["ok"] is False
    assert resps[0].get("oversized") is True
    assert conn in decoders
    assert server.metrics.faults.get("frame_oversized", 0) >= 1


def test_chaos_request_gated_off_by_default(server):
    resp = server.handle_request({"type": "chaos", "action": "nan_lane",
                                  "tenant": "whatever"})
    assert resp["ok"] is False and "chaos_enabled" in resp["error"]


def _nan_pair(server, shift_a, shift_b):
    """Submit two tenants into one bucket, run one healthy round, poison
    A's lane; returns (tenant_a, tenant_b) after the drain."""
    from skellysim_tpu.guard import chaos as chaos_mod

    ra = _submit(server, _tenant_cfg(shift_a))
    rb = _submit(server, _tenant_cfg(shift_b))
    server.tick()   # one healthy round for both
    chaos_mod.nan_lane_of(server.buckets[0].scheduler, ra["tenant"])
    _drain(server)
    return ra["tenant"], rb["tenant"]


def test_nan_tenant_fails_sibling_finishes(server):
    """ISSUE-9 acceptance pin, fast half: a NaN injected into tenant A's
    lane yields status=failed for A with a nonzero nonfinite verdict —
    surfaced in status/stats, a structured terminal stream, never a hang
    — while its bucket sibling finishes healthy. (The sibling's BITWISE
    sequential parity is the slow half below; cross-lane bitwise
    isolation is also pinned cheaply in test_ensemble.py.)"""
    from skellysim_tpu.guard import verdict

    ta, tb = _nan_pair(server, 0.25, 0.45)
    sa = server.handle_request({"type": "status", "tenant": ta})
    assert sa["status"] == "failed"
    assert sa["health"] & verdict.NONFINITE
    assert "nonfinite" in sa["verdict"]
    sb = server.handle_request({"type": "status", "tenant": tb})
    assert sb["status"] == "finished" and sb["health"] == 0
    # failed tenant: structured terminal stream, not a hang
    resp = server.handle_request({"type": "stream", "tenant": ta})
    assert resp["ok"] and resp["eof"] is True
    stats = server.metrics.stats()
    assert stats["retire_reasons"].get("failed", 0) >= 1
    assert stats["faults"].get("lane_failed", 0) >= 1
    assert stats["compiles_after_warm"] == 0


@pytest.mark.slow  # sequential-reference System build + run
def test_nan_tenant_sibling_streams_bitwise(server):
    """ISSUE-9 acceptance pin, slow half: the surviving sibling's streamed
    trajectory is BITWISE equal to its uninterrupted sequential
    `System.run` output."""
    cfg_b = _tenant_cfg(0.65)
    ta, tb = _nan_pair(server, 0.6, 0.65)
    sa = server.handle_request({"type": "status", "tenant": ta})
    assert sa["status"] == "failed"
    assert _stream(server, tb) == _sequential_frames(cfg_b)
    assert server.metrics.stats()["compiles_after_warm"] == 0


def test_status_surfaces_loss_of_accuracy_and_dt_underflow_fields(server):
    """Satellite pin: the `/status` schema carries the solver-health
    fields (they used to die in the metrics JSONL)."""
    r = _submit(server, _tenant_cfg(0.55))
    _drain(server)
    st = server.handle_request({"type": "status", "tenant": r["tenant"]})
    assert st["ok"]
    for key in ("health", "verdict", "loss_of_accuracy_steps",
                "dt_underflow"):
        assert key in st, key
    assert st["health"] == 0 and st["verdict"] == []
    assert st["dt_underflow"] is False


def test_journal_roundtrip_and_torn_tail(tmp_path):
    """Write-ahead journal: latest-wins replay, terminal entries inherit
    the last snapshot, and a torn final frame (crash mid-append) loses
    only that frame."""
    from skellysim_tpu.serve.journal import TenantJournal, replay

    p = tmp_path / "j.bin"
    with TenantJournal(str(p)) as j:
        j.record("admit", "tA", bucket=1, t_final=0.5, status="queued",
                 frame=b"F0")
        j.record("checkpoint", "tA", bucket=1, t_final=0.5,
                 status="running", frame=b"F1", t=0.25)
        j.record("admit", "tB", bucket=1, t_final=0.5, status="queued",
                 frame=b"G0")
        j.record("retire", "tB", bucket=1, t_final=0.5, status="finished",
                 t=0.5, health=0)
    entries = replay(str(p))
    assert entries["tA"]["status"] == "running"
    assert bytes(entries["tA"]["frame"]) == b"F1"
    assert entries["tB"]["status"] == "finished"
    # terminal entry without a frame inherits the last snapshot
    assert bytes(entries["tB"]["frame"]) == b"G0"

    data = p.read_bytes()
    p.write_bytes(data[:-3])  # tear the final frame
    entries2 = replay(str(p))
    assert entries2["tA"]["status"] == "running"
    assert entries2["tB"]["status"] == "queued"  # retire entry was torn

    assert replay(str(tmp_path / "missing.bin")) == {}


def _journal_entry_count(path) -> int:
    n = 0
    with open(path, "rb") as fh:
        while True:
            try:
                buf = protocol.read_frame(fh)
            except ValueError:
                break
            if not buf:
                break
            n += 1
    return n


def test_journal_recovery_evicts_unreadmittable_live_records(tmp_path):
    """A live-status journal record whose bucket no longer exists on the
    restarted server must restore as terminal `evicted` — never a zombie
    `running` tenant no scheduler drives (clients would poll it
    forever)."""
    from skellysim_tpu.serve.journal import TenantJournal

    wal = tmp_path / "wal.bin"
    with TenantJournal(str(wal)) as j:
        # bucket that does not exist on the restarted server
        j.record("checkpoint", "ghost", bucket=999, t_final=1.0,
                 status="running", frame=b"not-a-real-frame", t=0.5)
        # right bucket (capacity 1 = the base fiber count), junk snapshot:
        # the decode failure must degrade, not make the server unbootable
        j.record("checkpoint", "junk", bucket=1, t_final=1.0,
                 status="running", frame=b"also-not-a-frame", t=0.5)
    srv = SimulationServer(
        _tenant_cfg(), warmup=False,
        serve_cfg=schema.ServeConfig(max_lanes=2, batch_impl="unroll",
                                     journal_path=str(wal)))
    for tid in ("ghost", "junk"):
        st = srv.handle_request({"type": "status", "tenant": tid})
        assert st["ok"] and st["status"] == "evicted", (tid, st)
    assert not srv.any_live()
    # and the compacted journal carries exactly one record per tenant
    assert _journal_entry_count(str(wal)) == 2


@pytest.mark.slow  # builds two fresh servers (cold compiles)
def test_journal_crash_recovery_matches_unkilled_run(tmp_path):
    """ISSUE-9 acceptance pin, in-process: abandon a journaling server
    mid-flight (the kill -9 analogue — nothing is flushed beyond what the
    WAL already wrote), restart on the same journal, and the re-admitted
    tenant finishes with a final state BITWISE equal to the uninterrupted
    run's; terminal records survive too."""
    cfg = _tenant_cfg(0.35)
    scfg = schema.ServeConfig(max_lanes=2, batch_impl="unroll",
                              journal_path=str(tmp_path / "wal.bin"),
                              journal_every=2)
    srv = SimulationServer(cfg, serve_cfg=scfg)
    r = _submit(srv, cfg)
    tid = r["tenant"]
    done = _submit(srv, _tenant_cfg(0.05))
    srv.tick()
    srv.tick()
    srv.tick()   # tenant mid-flight, >= 1 checkpoint written
    st = srv.handle_request({"type": "status", "tenant": tid})
    assert st["status"] == "running" and 0.0 < st["t"] < st["t_final"]
    srv.journal.close()   # abandon srv: its in-memory state dies here

    srv2 = SimulationServer(cfg, serve_cfg=scfg)
    # recovery COMPACTED the journal: exactly one entry per known tenant
    assert _journal_entry_count(scfg.journal_path) == 2
    st2 = srv2.handle_request({"type": "status", "tenant": tid})
    assert st2["ok"] and st2["status"] in ("queued", "running")
    assert st2["t"] <= st["t"]  # replays from the checkpoint, never ahead
    _drain(srv2)
    st3 = srv2.handle_request({"type": "status", "tenant": tid})
    assert st3["status"] == "finished"
    # the resumed final state == the uninterrupted run's final state
    snap = srv2.handle_request({"type": "snapshot", "tenant": tid})
    assert bytes(snap["frame"]) == _sequential_frames(cfg)[-1]
    assert srv2.handle_request(
        {"type": "stats"})["stats"]["journal"] is True
    del done


@pytest.mark.slow  # subprocess server boot (compile) + TCP round-trips
def test_socket_end_to_end(tmp_path):
    """The CI smoke's contract, in-tree: spawn `python -m
    skellysim_tpu.serve`, admit two tenants over TCP, stream >= 2 frames
    each, clean shutdown with exit code 0."""
    import os
    import subprocess
    import sys

    from skellysim_tpu.serve.client import SpawnedServer

    cfg_path = str(tmp_path / "serve_config.toml")
    base = _tenant_cfg()
    base.save(cfg_path)
    with open(cfg_path, "a") as fh:
        fh.write("\n[serve]\nmax_lanes = 2\nbatch_impl = \"unroll\"\n")

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo  # skip the session's .axon_site sitecustomize
    with SpawnedServer(cfg_path, env=env) as srv:
        with srv.client() as c:
            tids = [c.submit(_toml(_tenant_cfg(s)))["tenant"]
                    for s in (0.1, 0.3)]
            for tid in tids:
                st = c.wait(tid, timeout=120)
                assert st["status"] == "finished"
                frames = c.stream(tid)["frames"]
                assert len(frames) >= 2
            stats = c.stats()
            assert stats["compiles_after_warm"] == 0
            # skelly-pulse SLO histograms, folded from REAL events over
            # the wire: admission wait + round wall distributions report
            # percentiles, and the prometheus rendering carries them
            for key in ("admission_wait_s", "round_wall_s_hist",
                        "frame_stream_s"):
                slo = stats[key]
                for q in ("p50", "p95", "p99"):
                    assert q in slo, (key, slo)
                assert slo["p50"] <= slo["p95"] <= slo["p99"]
            assert stats["admission_wait_s"]["n"] == stats["admitted"] == 2
            assert stats["round_wall_s_hist"]["n"] == stats["rounds"] > 0
            assert stats["frame_stream_s"]["n"] >= 2  # one drain per tenant
            prom = c.stats_prometheus()
            assert "skellysim_serve_admission_wait_seconds_bucket" in prom
            assert 'le="+Inf"' in prom
            assert "skellysim_serve_compiles_after_warm_total 0" in prom
        rc = srv.stop()
    assert rc == 0


# --------------------------------------------- dynamic-instability serving


def _di_cfg(n_sites=4, nucleation_rate=200.0, t_final=0.04, seed=130319):
    """Fiber-less confined-class DI tenant scene: one analytic nucleating
    body with EMBEDDED sites (the wire contract — docs/scenarios.md)."""
    cfg = Config()
    cfg.params.eta = 1.0
    cfg.params.dt_initial = 0.02
    cfg.params.dt_write = 0.02
    cfg.params.t_final = t_final
    cfg.params.gmres_tol = 1e-10
    cfg.params.adaptive_timestep_flag = False
    cfg.params.seed = seed
    di = cfg.params.dynamic_instability
    di.n_nodes = 8
    di.v_growth = 0.2
    di.f_catastrophe = 0.0
    di.nucleation_rate = nucleation_rate
    di.min_length = 0.3
    di.radius = 0.0125
    di.bending_rigidity = 0.01
    rng = np.random.default_rng(7)
    sites = rng.standard_normal((n_sites, 3))
    sites = 0.4 * sites / np.linalg.norm(sites, axis=1, keepdims=True)
    cfg.bodies = [Body(shape="sphere", radius=0.4, n_nodes=40,
                       n_nucleation_sites=n_sites,
                       nucleation_sites=sites.ravel().tolist())]
    return cfg


def test_di_tenant_admission_rules():
    """DI serve admission (docs/scenarios.md): bodies stay rejected on a
    non-DI server; a DI server admits ANALYTIC bodies with embedded sites
    and rejects non-analytic surfaces and unembedded generated sites."""
    from skellysim_tpu.serve import tenants as tenants_mod

    text = _toml(_di_cfg())
    with pytest.raises(ValueError, match="dynamic"):
        tenants_mod.parse_tenant_config(text, di_enabled=False)
    out = tenants_mod.parse_tenant_config(text, di_enabled=True)
    assert out.bodies and out.bodies[0].nucleation_sites
    bad = _di_cfg()
    bad.bodies[0].shape = "deformable"
    with pytest.raises(ValueError, match="analytic"):
        tenants_mod.parse_tenant_config(_toml(bad), di_enabled=True)
    bad2 = _di_cfg()
    bad2.bodies[0].nucleation_sites = []
    with pytest.raises(ValueError, match="embed"):
        tenants_mod.parse_tenant_config(_toml(bad2), di_enabled=True)
    # fiber-less is legal ONLY with a nucleating body on a DI server
    nofib = _di_cfg()
    nofib.bodies = []
    with pytest.raises(ValueError, match="no fibers"):
        tenants_mod.parse_tenant_config(_toml(nofib), di_enabled=True)


@pytest.mark.slow  # warms two vmap coupled body-program buckets (~80 s)
def test_di_tenant_growth_reseat_and_finish():
    """Tentpole serve pin: a DI tenant (fiber-less, nucleating analytic
    body) admits onto a DI server, its nucleation burst outgrows the first
    capacity bucket, `_grow_tenant` reseats it onto the next bucket, and
    it finishes with a streamable trajectory + `growth_reseats` on
    /stats."""
    srv = SimulationServer(
        _di_cfg(), serve_cfg=schema.ServeConfig(max_lanes=1,
                                                batch_impl="vmap",
                                                bucket_capacities=[2, 4]))
    assert srv.di_enabled
    assert [b.capacity for b in srv.buckets] == [2, 4]
    r = _submit(srv, _di_cfg(seed=7), tenant="di0")
    assert r["tenant"] == "di0"
    _drain(srv)
    st = srv.handle_request({"type": "status", "tenant": "di0"})
    assert st["ok"] and st["status"] == "finished", st
    # 4 free sites at rate 200 make the first nucleation burst ~surely
    # outgrow the 2-slot bucket: the growth reseat moved the tenant 2 -> 4
    stats = srv.handle_request({"type": "stats"})["stats"]
    assert stats["growth_reseats"] >= 1, stats
    t = srv.registry.get("di0")
    assert t.bucket == 4
    frames = _stream(srv, "di0")
    assert len(frames) >= 2
    # the snapshot survives as a resume point with its RNG streams
    snap = srv.handle_request({"type": "snapshot", "tenant": "di0"})
    assert snap["ok"] and snap["frame"]
