"""skelly-flight: the device-side physics flight recorder + anomaly
provenance (obs/flight.py, docs/observability.md "Flight recorder").

Pins the ISSUE-15 acceptance surface:

* `Params.flight_window = 0` (the default) is the PRE-FLIGHT program:
  `SimState.flight` is absent and the armed twin's physics is bitwise
  identical to the disabled one (the recorder must observe, never
  perturb);
* ring wrap chronology under the ensemble vmap path (the gmres-history
  wrap test's mirror), including per-member counts through the scheduler;
* anomaly provenance names the poisoned field/fiber/node, on the
  single-chip step, the ensemble failure records, and the fault events;
* the SPMD ring analyzes replication-clean (`audit.repflow`) and matches
  the single-chip row;
* host tooling: torn-trailing-line tolerance, the summarize physics
  table, the `obs flight` blast-radius report, timeline counter tracks.
"""

from __future__ import annotations

import json

import jax.numpy as jnp
import numpy as np
import pytest

from skellysim_tpu.audit import fixtures
from skellysim_tpu.obs import flight as flight_mod


@pytest.fixture(scope="module")
def armed_system():
    """One armed (K=4) system + its compiled step, shared by the
    single-chip tests (the fixture step compiles once per module)."""
    system = fixtures.make_system(flight_window=4)
    return system


def _poisoned(state, field, fiber, node):
    x = np.asarray(state.fibers.x).copy()
    t = np.asarray(state.fibers.tension).copy()
    if field == "fiber_x":
        x[fiber, node, 1] = np.nan
    elif field == "fiber_tension":
        t[fiber, node] = np.inf
    return state._replace(fibers=state.fibers._replace(
        x=jnp.asarray(x), tension=jnp.asarray(t)))


# ------------------------------------------------------------ host decode

def test_ring_rows_wrap_chronology_host():
    """Wrap decode mirrors `history_rows`: count > K keeps the LAST K
    rows, rotated oldest-first; ids decode to ints, NaN floats to None."""
    K, D = 4, len(flight_mod.FLIGHT_FIELDS)
    rows = np.full((K, D), np.nan, dtype=np.float32)
    for c in range(6):  # rows written at t = c
        rows[c % K] = np.arange(D, dtype=np.float32) * 0 + c
    decoded = flight_mod.ring_rows(rows, 6)
    assert [r["t"] for r in decoded] == [2.0, 3.0, 4.0, 5.0]
    assert decoded[-1]["strain_fiber"] == 5          # id column -> int
    assert flight_mod.ring_rows(rows, 0) == []
    assert flight_mod.ring_rows(None, 3) == []
    # NaN floats decode to None; provenance decodes from the id columns
    one = np.full(D, np.nan, dtype=np.float32)
    one[flight_mod.FLIGHT_FIELDS.index("prov_field")] = 1
    one[flight_mod.FLIGHT_FIELDS.index("prov_fiber")] = 2
    one[flight_mod.FLIGHT_FIELDS.index("prov_node")] = 3
    d = flight_mod.decode_row(one)
    assert d["max_strain"] is None
    assert d["provenance"] == {"field": "fiber_x", "fiber": 2, "node": 3}
    # ±inf decodes to JSON-safe strings: the blow-up signal survives while
    # the JSONL streams stay RFC-8259 (no bare `Infinity` tokens)
    one[flight_mod.FLIGHT_FIELDS.index("max_strain")] = np.inf
    one[flight_mod.FLIGHT_FIELDS.index("min_clearance")] = -np.inf
    d = flight_mod.decode_row(one)
    assert d["max_strain"] == "inf" and d["min_clearance"] == "-inf"
    assert "Infinity" not in json.dumps(d)


def test_window_zero_state_is_preflight(armed_system):
    """flight_window=0 keeps SimState.flight ABSENT (None leaf ⇒ the
    pytree, and so the compiled program, is the pre-flight one) and
    ensure_flight arms/strips/re-arms across window changes."""
    off = fixtures.make_system()
    st = fixtures.free_state(off)
    assert st.flight is None
    armed = fixtures.free_state(armed_system)
    assert armed.flight is not None
    assert armed.flight.rows.shape == (4, len(flight_mod.FLIGHT_FIELDS))
    # ensure_flight normalization: strip, arm, re-arm on size mismatch
    assert off.ensure_flight(armed).flight is None
    re = armed_system.ensure_flight(st)
    assert re.flight is not None and int(re.flight.count) == 0
    bigger = fixtures.make_system(flight_window=8)
    assert bigger.ensure_flight(armed).flight.rows.shape[0] == 8


def test_armed_step_bitwise_physics_and_ring(armed_system):
    """The recorder observes, never perturbs: K=4 vs K=0 trajectories are
    BITWISE identical, while the ring records one chronological row per
    trial with the expected diagnostics."""
    off = fixtures.make_system()
    s_off = fixtures.free_state(off)
    s_on = fixtures.free_state(armed_system)
    for i in range(3):
        n_off, sol_off, i_off = off.step(s_off)
        n_on, sol_on, i_on = armed_system.step(s_on)
        assert np.array_equal(np.asarray(sol_off), np.asarray(sol_on))
        assert np.array_equal(np.asarray(n_off.fibers.x),
                              np.asarray(n_on.fibers.x))
        s_off = n_off._replace(time=n_off.time + n_off.dt)
        s_on = n_on._replace(time=n_on.time + n_on.dt)
    rows = flight_mod.ring_rows(s_on.flight.rows, s_on.flight.count)
    assert int(s_on.flight.count) == 3 and len(rows) == 3
    ts = [r["t"] for r in rows]
    assert ts == sorted(ts)
    last = rows[-1]
    assert last["health"] == 0 and last["provenance"] is None
    assert last["solution_norm"] > 0
    assert last["max_speed"] > 0
    assert last["min_clearance"] is None      # free-space scene: no wall
    assert 0 <= last["strain_fiber"] < 16
    assert last["dt_used"] == pytest.approx(float(s_on.dt), rel=1e-6)


def test_provenance_names_field_fiber_node(armed_system):
    """A NaN planted at fiber 2 / node 3 localizes as (fiber_x, 2, 3) —
    exact coordinates, not just 'a lane died'; with BOTH a position and a
    tension poisoned, the scan's priority order names fiber_x first. Same
    compiled program throughout (poison changes values, not shapes)."""
    base = fixtures.free_state(armed_system)
    for fiber, node in ((2, 3), (0, 7)):
        bad = _poisoned(base, "fiber_x", fiber, node)
        new_state, _, info = armed_system.step(bad)
        assert int(info.health) & 1            # NONFINITE
        row = flight_mod.last_row(np.asarray(new_state.flight.rows),
                                  new_state.flight.count)
        assert row["provenance"] == {"field": "fiber_x", "fiber": fiber,
                                     "node": node}, row
    both = _poisoned(_poisoned(base, "fiber_tension", 1, 5),
                     "fiber_x", 2, 3)
    new_state, _, info = armed_system.step(both)
    assert int(info.health) & 1
    row = flight_mod.last_row(np.asarray(new_state.flight.rows),
                              new_state.flight.count)
    assert row["provenance"] == {"field": "fiber_x", "fiber": 2, "node": 3}


@pytest.mark.slow
def test_provenance_shell_nodes_vs_benign_density():
    """On the coupled scene: poisoned shell GEOMETRY (the wall every flow
    evaluates against) fails the solve and localizes as shell_nodes with
    the node index, while a poisoned shell DENSITY alone is benign — the
    Krylov solve starts from zero and overwrites it, so health stays 0
    and the recorder must not cry wolf."""
    system = fixtures.make_system(shell=True, flight_window=4)
    state = fixtures.coupled_state(system)
    nodes = np.asarray(state.shell.nodes).copy()
    nodes[5, 2] = np.nan
    bad = state._replace(shell=state.shell._replace(
        nodes=jnp.asarray(nodes)))
    nb, _, ib = system.step(bad)
    assert int(ib.health) & 1
    row = flight_mod.last_row(np.asarray(nb.flight.rows), nb.flight.count)
    assert row["provenance"] == {"field": "shell_nodes", "fiber": -1,
                                 "node": 5}
    rho = np.asarray(state.shell.density).copy()
    rho[17] = np.inf
    benign = state._replace(shell=state.shell._replace(
        density=jnp.asarray(rho)))
    n2, _, i2 = system.step(benign)
    assert int(i2.health) == 0
    assert np.isfinite(np.asarray(n2.shell.density)).all()


# --------------------------------------------------------- ensemble front

def test_ensemble_vmap_ring_wrap_and_failure_payload():
    """The gmres-history wrap test's mirror on the ensemble path: K=3
    per-member rings ride the vmapped state, wrap chronologically, reject
    /quarantine keeps the fatal row, and the scheduler's failure record +
    fault event carry the tail + provenance while the sibling finishes."""
    from skellysim_tpu.ensemble.runner import EnsembleRunner
    from skellysim_tpu.ensemble.scheduler import (EnsembleScheduler,
                                                  MemberSpec)
    from skellysim_tpu.guard import chaos
    from skellysim_tpu.io.ensemble_io import ENSEMBLE_FAILURE_FIELDS
    from skellysim_tpu.obs import tracer as obs_tracer
    from skellysim_tpu.system import BackgroundFlow

    system = fixtures.make_system(flight_window=3)
    runner = EnsembleRunner(system)

    def member(seed):
        return system.make_state(
            fibers=fixtures.make_fibers(n_fibers=4, n_nodes=8, seed=seed),
            background=BackgroundFlow.make(uniform=(1.0, 0.0, 0.0),
                                           dtype=jnp.float64))

    records = []
    tracer = obs_tracer.Tracer()
    with obs_tracer.use(tracer):
        sched = EnsembleScheduler(
            runner, [MemberSpec("m0", member(1), 6e-3),
                     MemberSpec("m1", member(2), 6e-3)],
            2, metrics=records.append, on_failure="retire")
        sched.poll()
        sched.poll()
        # rings wrapped past K=3 need >3 rounds for m1; poison m0 now
        sched.ens = chaos.poison_lane(sched.ens, 0)
        sched.run()

    steps = [r for r in records if r.get("event") == "step"]
    assert steps and all("flight" in r for r in steps)
    healthy = [r["flight"] for r in steps if r["member"] == "m1"]
    assert all(f["health"] == 0 for f in healthy)
    # wrap chronology per member: m1 ran 6 rounds into a K=3 ring
    fl = sched.ens.states.flight
    lane1 = sched.retired.index("m1") >= 0  # m1 retired; read its record
    del lane1
    fails = [r for r in records if r.get("event") == "failed"]
    assert len(fails) == 1 and fails[0]["member"] == "m0"
    assert set(fails[0]) == set(ENSEMBLE_FAILURE_FIELDS)
    payload = fails[0]["flight"]
    assert payload["provenance"] == {"field": "fiber_x", "fiber": 0,
                                     "node": 0}
    assert payload["tail"] and payload["tail"][-1]["health"] & 1
    # the quarantined round's row SURVIVED the lane freeze (the fatal row
    # is the evidence — the runner merges rings on `running`, not accept)
    ts = [r["t"] for r in payload["tail"]]
    assert ts == sorted(ts)
    faults = [e for e in tracer.events if e.get("ev") == "fault"
              and e.get("kind") == "lane_failed"]
    assert faults and faults[0]["prov_field"] == "fiber_x"
    assert faults[0]["prov_fiber"] == 0
    # flight telemetry events rode the stream (timeline counter source)
    assert any(e.get("ev") == "flight" for e in tracer.events)
    assert fl is not None


# ------------------------------------------------------------- SPMD front

def test_spmd_armed_build_analyzes_replication_clean():
    """The armed mesh program writes a REPLICATED ring: every reduction
    is psum'd/pmax'd, the provenance tie-break is an index-min — the
    replication analyzer proves the build deadlock-free with zero
    findings (the ISSUE-15 'repflow analyzes the SPMD ring clean' pin)."""
    from skellysim_tpu.audit import repflow
    from skellysim_tpu.parallel import shard_state
    from skellysim_tpu.parallel.mesh import make_mesh
    from skellysim_tpu.parallel.spmd import build_spmd_step

    mesh = make_mesh(2)
    system = fixtures.make_system(flight_window=32)
    state = shard_state(fixtures.free_state(system), mesh)
    fn = build_spmd_step(system, mesh, state, donate=False)
    report = repflow.analyze(fn.trace(state).jaxpr)
    assert report.findings == []
    assert len(report.regions) == 1
    assert report.regions[0].replicated_outputs > 0


@pytest.mark.slow
def test_spmd_ring_matches_single_chip():
    """One d2 step's flight row agrees with the single-chip row: same
    argmax fiber id (globalized across shards), same extrema to
    f32-reduction roundoff — all shards having written the identical
    replicated ring."""
    from skellysim_tpu.parallel import shard_state
    from skellysim_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(2)
    system = fixtures.make_system(flight_window=4)
    state = shard_state(fixtures.free_state(system), mesh)
    new_state, _, _ = system.step_spmd(state, mesh, donate=False)
    row = flight_mod.last_row(np.asarray(new_state.flight.rows),
                              np.asarray(new_state.flight.count))

    s1 = fixtures.make_system(flight_window=4)
    n1, _, _ = s1.step(fixtures.free_state(s1))
    ref = flight_mod.last_row(np.asarray(n1.flight.rows), n1.flight.count)
    assert row["strain_fiber"] == ref["strain_fiber"]
    assert row["max_speed"] == pytest.approx(ref["max_speed"], rel=1e-5)
    assert row["solution_norm"] == pytest.approx(ref["solution_norm"],
                                                 rel=1e-4)
    assert row["health"] == ref["health"] == 0


# ----------------------------------------------------------- host tooling

def _metrics_line(member=None, flight=None, **over):
    rec = {"step": 0, "t": 0.1, "dt": 0.01, "iters": 3, "gmres_cycles": 1,
           "collective_rounds": 11, "residual": 1e-11,
           "residual_true": 1e-11, "fiber_error": 1e-9, "accepted": True,
           "refines": 0, "loss_of_accuracy": False, "health": 0,
           "guard_retries": 0, "nucleations": 0, "catastrophes": 0,
           "active_fibers": 0, "wall_s": 0.1, "wall_ms": 100.0,
           "gmres_history": [], "flight": flight}
    if member is not None:
        rec.update(event="step", member=member, lane=0, round=0)
    rec.update(over)
    return json.dumps(rec)


def _flight_dict(**over):
    d = {"t": 0.1, "dt_used": 0.01, "max_strain": 1e-9, "strain_fiber": 3,
         "max_speed": 0.5, "min_clearance": 0.8, "body_norm": 0.0,
         "solution_norm": 12.5, "residual_true": 1e-11, "health": 0,
         "prov_field": 0, "prov_fiber": -1, "prov_node": -1,
         "provenance": None}
    d.update(over)
    return d


def test_summarize_torn_tail_and_physics_table(tmp_path):
    """A kill-9-torn trailing line is tolerated (reported, never a crash
    or an 'unparseable' count), and flight rows render the physics table;
    a metrics flight column and its telemetry-event twin dedupe."""
    from skellysim_tpu.obs.summarize import summarize_files

    path = tmp_path / "metrics.jsonl"
    flight = _flight_dict(max_strain=2e-3, min_clearance=-0.25)
    lines = [_metrics_line(flight=flight),
             json.dumps(dict({"ev": "flight", "member": "run"}, **flight)),
             _metrics_line(flight=None, t=0.2)[:37]]  # torn mid-record
    path.write_text("\n".join(lines) + "\n")
    out = summarize_files([str(path)])
    assert "torn trailing line" in out
    assert "unparseable" not in out
    assert "physics diagnostics" in out
    # 1 step, not 2: the metrics column and the flight event are one trial
    line = next(ln for ln in out.splitlines() if ln.startswith("run "))
    assert line.split()[1] == "1"
    assert "-0.25" in line
    # mid-file garbage is still reported as unparseable
    path2 = tmp_path / "garbled.jsonl"
    path2.write_text("{nope}\n" + _metrics_line(flight=None) + "\n")
    out2 = summarize_files([str(path2)])
    assert "1 unparseable" in out2 and "torn" not in out2


def test_flight_report_blast_radius(tmp_path):
    """`obs flight` renders the fault trajectory + offender coordinates
    from an ensemble metrics stream, tolerating a torn tail; exit paths
    covered via the CLI entry."""
    from skellysim_tpu.obs.cli import main as obs_main

    path = tmp_path / "ens.jsonl"
    tail = [_flight_dict(t=0.1), _flight_dict(t=0.11),
            _flight_dict(t=0.12, health=1, max_strain="inf",
                         prov_field=1, prov_fiber=2, prov_node=7,
                         provenance={"field": "fiber_x", "fiber": 2,
                                     "node": 7})]
    lines = [_metrics_line(member="m0", flight=tail[0]),
             _metrics_line(member="m1", flight=_flight_dict()),
             json.dumps({"event": "failed", "member": "m0", "lane": 0,
                         "t": 0.12, "steps": 3, "frames": 0, "health": 1,
                         "verdict": "nonfinite",
                         "flight": {"tail": tail,
                                    "provenance": tail[-1]["provenance"]}}),
             # the SAME fault's telemetry event (a metrics+trace pair fed
             # together must count the fault once, not twice)
             json.dumps({"ev": "fault", "ts": 2.0, "kind": "lane_failed",
                         "member": "m0", "health": 1,
                         "verdict": "nonfinite", "prov_field": "fiber_x",
                         "prov_fiber": 2, "prov_node": 7}),
             '{"torn']
    path.write_text("\n".join(lines))
    report = flight_mod.render_flight_report([str(path)])
    assert "m0: FAULT (nonfinite)" in report
    assert "field=fiber_x fiber 2 node 7" in report
    assert "trajectory into the fault" in report
    assert "healthy members (1)" in report and "m1:" in report
    assert "fiber_x=1" in report          # fault-localization counters
    assert "torn trailing line" in report
    assert obs_main(["flight", str(path)]) == 0
    assert obs_main(["flight", str(tmp_path / "missing.jsonl")]) == 2
    # no flight data at all is a clean empty report, not an error
    empty = tmp_path / "empty.jsonl"
    empty.write_text(_metrics_line(flight=None) + "\n")
    assert "no flight-recorder records" in flight_mod.render_flight_report(
        [str(empty)])


def test_timeline_flight_counter_tracks(tmp_path):
    """`obs timeline` renders flight telemetry events as perfetto COUNTER
    tracks next to the span slices."""
    from skellysim_tpu.obs.timeline import write_timeline

    trace = tmp_path / "trace.jsonl"
    evs = [{"ev": "telemetry", "ts": 0.0, "version": 1},
           {"ev": "span", "ts": 1.0, "dur_s": 0.5, "name": "step",
            "path": "run/step"},
           dict({"ev": "flight", "ts": 1.0, "member": "m0"},
                **_flight_dict()),
           dict({"ev": "flight", "ts": 1.5, "member": "m0"},
                **_flight_dict(max_strain=2e-9))]
    trace.write_text("\n".join(json.dumps(e) for e in evs) + "\n")
    out = tmp_path / "tl.json"
    counts = write_timeline([str(trace)], str(out))
    assert counts["counters"] > 0
    doc = json.loads(out.read_text())
    counters = [e for e in doc["traceEvents"] if e.get("ph") == "C"]
    names = {e["name"] for e in counters}
    assert "flight:max_strain [m0]" in names
    assert all("value" in e["args"] for e in counters)


def test_serve_status_and_stats_surface_flight():
    """The serve front, in-process: a chaos-poisoned tenant's `status`
    answers the flight tail + provenance, `/stats` counts the offender
    field, and the bucket sibling finishes untouched."""
    from skellysim_tpu.config import BackgroundSource, Config, Fiber, schema
    from skellysim_tpu.config.toml_io import dumps as toml_dumps
    from skellysim_tpu.guard import chaos as chaos_mod
    from skellysim_tpu.serve.server import SimulationServer

    def scene(shift):
        cfg = Config()
        cfg.params.dt_initial = cfg.params.dt_write = 0.005
        cfg.params.t_final = 0.02
        cfg.params.gmres_tol = 1e-10
        cfg.params.adaptive_timestep_flag = False
        cfg.params.flight_window = 4
        fib = Fiber(n_nodes=8, length=1.0, bending_rigidity=0.01)
        fib.fill_node_positions(np.array([shift, 0.0, 0.0]),
                                np.array([0.0, 0.0, 1.0]))
        cfg.fibers = [fib]
        cfg.background = BackgroundSource(uniform=[1.0, 0.0, 0.0])
        return cfg

    serve_cfg = schema.ServeConfig(max_lanes=2, batch_impl="unroll")
    server = SimulationServer(scene(0.0), serve_cfg=serve_cfg)
    ta = server.handle_request(
        {"type": "submit", "config": toml_dumps(schema.unpack(scene(0.1))),
         "t_final": 0.05})["tenant"]
    tb = server.handle_request(
        {"type": "submit", "config": toml_dumps(schema.unpack(scene(0.3))),
         "t_final": 0.05})["tenant"]
    server.tick()
    sched = server.buckets[0].scheduler
    chaos_mod.nan_lane_of(sched, ta)
    for _ in range(30):
        if not server.any_live():
            break
        server.tick()
    sa = server.handle_request({"type": "status", "tenant": ta})
    sb = server.handle_request({"type": "status", "tenant": tb})
    assert sa["status"] == "failed"
    assert sa["flight"]["provenance"] == {"field": "fiber_x", "fiber": 0,
                                          "node": 0}
    assert sa["flight"]["tail"][-1]["health"] & 1
    assert sb["status"] == "finished" and sb["flight"] is None
    stats = server.handle_request({"type": "stats"})["stats"]
    assert stats["fault_fields"] == {"fiber_x": 1}
