"""skelly-ensemble: batched execution + continuous-batching scheduler.

Pins the ISSUE-2 acceptance criteria:

* an ensemble of B >= 8 small systems on the 8-device virtual CPU mesh
  produces per-member trajectories BITWISE identical to B sequential
  single-run `System.run` executions with the same per-member dt sequences
  (masked adaptive stepping changes nothing observable) — `batch_impl=
  "unroll"`, including through lane backfills;
* the batched step traces exactly once across backfills
  (`testing.trace_counting_jit`);
* GMRES's masked-convergence semantics under vmap: a converged member's
  solution/iteration count is unperturbed by slower members still
  iterating.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from __graft_entry__ import _make_system
from skellysim_tpu.ensemble import (EnsembleRunner, EnsembleScheduler,
                                    MemberSpec, lane_state, stack_states)
from skellysim_tpu.io.ensemble_io import (ENSEMBLE_RETIRE_FIELDS,
                                          ENSEMBLE_START_FIELDS,
                                          ENSEMBLE_STEP_FIELDS)
from skellysim_tpu.io.trajectory import frame_bytes
from skellysim_tpu.testing import trace_counting_jit
from skellysim_tpu.utils.rng import SimRNG


def _ensemble_system():
    """Small adaptive free-fiber system: 1 x 8-node fiber, f64 (tiny B/N —
    these tests must fit the per-commit fast tier)."""
    system, state = _make_system(n_fibers=1, n_nodes=8, dtype=jnp.float64)
    system.params = dataclasses.replace(
        system.params, adaptive_timestep_flag=True, dt_max=4e-3,
        dt_write=2e-3, fiber_error_tol=0.1, t_final=1.0)
    return system, state


#: lane count for the acceptance pin (the ISSUE's "B >= 8 ... on the
#: 8-device virtual CPU mesh") and member count (> B, so retirement +
#: backfill churn through the lanes)
B_LANES = 8
N_MEMBERS = 10


def _members(base_state, n=N_MEMBERS):
    """n members with distinct geometry, dt sequences, and end times."""
    members = []
    for i in range(n):
        st = base_state._replace(
            fibers=base_state.fibers._replace(x=base_state.fibers.x + 0.01 * i),
            dt=jnp.asarray(1e-3 * (1 + 0.1 * i), dtype=jnp.float64))
        members.append(MemberSpec(member_id=f"m{i}", state=st,
                                  t_final=0.004 + 0.002 * i))
    return members


@pytest.fixture(scope="module")
def scene():
    system, base_state = _ensemble_system()
    return system, _members(base_state)


@pytest.fixture(scope="module")
def runners(scene):
    """One runner per execution plan, shared module-wide so every test at
    lane count B reuses the same compiled batched step."""
    system, _ = scene
    return {"unroll": EnsembleRunner(system, batch_impl="unroll"),
            "vmap": EnsembleRunner(system, batch_impl="vmap")}


@pytest.fixture(scope="module")
def sequential_frames(scene):
    """Reference: each member through the sequential adaptive loop (one solo
    System — its jit is t_final-independent, so one compile serves all)."""
    system, members = scene
    solo, _ = _ensemble_system()
    out = {}
    for m in members:
        solo.params = dataclasses.replace(system.params, t_final=m.t_final)
        frames = []
        solo.run(m.state,
                 writer=lambda s, sol, **kw: frames.append(frame_bytes(s)))
        out[m.member_id] = frames
    return out


def _drain(runner, members, batch, **kw):
    frames = {m.member_id: [] for m in members}
    records = []
    sched = EnsembleScheduler(
        runner, members, batch,
        writer=lambda mid, s, rng_state=None: frames[mid].append(
            frame_bytes(s)),
        metrics=records.append, **kw)
    retired = sched.run()
    return frames, records, retired, sched


@pytest.fixture(scope="module")
def unroll_drain(scene, runners):
    """One unroll-plan sweep shared by the parity and cross-plan tests."""
    _, members = scene
    return _drain(runners["unroll"], members, batch=B_LANES)


def test_unroll_trajectories_bitwise_vs_sequential(scene, unroll_drain,
                                                   sequential_frames):
    """THE acceptance pin: B=8 lanes on the 8-device virtual CPU platform,
    10 members (so lanes retire + backfill mid-sweep), masked adaptive
    stepping — per-member frame sequences bitwise identical to 10
    sequential `System.run` executions."""
    _, members = scene
    frames, _, retired, _ = unroll_drain
    assert sorted(retired) == sorted(m.member_id for m in members)
    for m in members:
        seq = sequential_frames[m.member_id]
        ens = frames[m.member_id]
        assert len(seq) == len(ens) > 0, m.member_id
        for k, (a, b) in enumerate(zip(seq, ens)):
            assert a == b, (f"{m.member_id} frame {k} differs from the "
                            "sequential run (bytes)")


def test_vmap_matches_unroll_to_roundoff(scene, runners, unroll_drain):
    """The throughput plan agrees with the bit-reproducible plan to
    roundoff: same frame count, same accept/reject pattern, values tight."""
    _, members = scene
    f_unroll, r_unroll, _, _ = unroll_drain
    f_vmap, r_vmap, _, _ = _drain(runners["vmap"], members, batch=B_LANES)
    steps_u = [(r["member"], r["step"], r["accepted"]) for r in r_unroll
               if r["event"] == "step"]
    steps_v = [(r["member"], r["step"], r["accepted"]) for r in r_vmap
               if r["event"] == "step"]
    assert steps_u == steps_v
    from skellysim_tpu.io import eigen
    import msgpack

    for mid in f_unroll:
        assert len(f_unroll[mid]) == len(f_vmap[mid])
        for a, b in zip(f_unroll[mid], f_vmap[mid]):
            fa = eigen.decode_tree(msgpack.unpackb(a, raw=False))
            fb = eigen.decode_tree(msgpack.unpackb(b, raw=False))
            assert fa["time"] == fb["time"] and fa["dt"] == fb["dt"]
            np.testing.assert_allclose(np.asarray(fa["fibers"][1][0]["x_"]),
                                       np.asarray(fb["fibers"][1][0]["x_"]),
                                       rtol=1e-9, atol=1e-12)


def test_batched_step_traces_once_across_backfills(scene, runners):
    """Continuous batching's compiled-program contract: retiring members and
    backfilling lanes from the queue must reuse the one traced program."""
    _, members = scene
    runner = runners["vmap"]
    step = trace_counting_jit(runner.step_impl)
    sched = EnsembleScheduler(runner, members, batch=3, step_fn=step)
    retired = sched.run()
    assert sorted(retired) == sorted(m.member_id for m in members)
    assert sched.rounds > len(members) / 3  # several generations of lanes
    assert step.trace_count == 1, (
        "backfill retraced the batched step — a leaf swap changed its "
        "static signature")


def test_member_axis_shards_across_mesh(scene, runners):
    """B=8 members shard over the 8-device virtual CPU mesh (batch
    parallelism as the outer axis) and step to the same answer."""
    from skellysim_tpu.parallel import make_member_mesh, shard_ensemble

    _, members = scene
    runner = runners["vmap"]
    ens = runner.make_ensemble([m.state for m in members[:8]],
                               [m.t_final for m in members[:8]])
    mesh = make_member_mesh(8)
    sharded = shard_ensemble(ens, mesh)
    assert len(sharded.t_final.sharding.device_set) == 8
    out_ref, info_ref = runner.step(ens)
    out_sh, info_sh = runner.step(sharded)
    np.testing.assert_array_equal(np.asarray(info_ref.iters),
                                  np.asarray(info_sh.iters))
    np.testing.assert_allclose(np.asarray(out_sh.states.fibers.x),
                               np.asarray(out_ref.states.fibers.x),
                               rtol=1e-9, atol=1e-12)
    with pytest.raises(ValueError, match="not divisible"):
        shard_ensemble(runner.make_ensemble([members[0].state] * 3,
                                            [0.1] * 3), mesh)


def test_ensemble_metrics_schema(scene, runners):
    """Aggregated metrics JSONL schema: start/step/retire records carry
    exactly the documented keys (docs/ensemble.md)."""
    _, members = scene
    _, records, _, _ = _drain(runners["vmap"], members[:3], batch=B_LANES)
    kinds = {r["event"] for r in records}
    assert kinds == {"start", "step", "retire"}
    for r in records:
        if r["event"] == "start":
            assert set(r) == set(ENSEMBLE_START_FIELDS)
        elif r["event"] == "step":
            assert set(r) == set(ENSEMBLE_STEP_FIELDS)
        else:
            assert set(r) == set(ENSEMBLE_RETIRE_FIELDS)
    # step indices are contiguous per member from 0
    for m in members[:3]:
        steps = [r["step"] for r in records
                 if r["event"] == "step" and r["member"] == m.member_id]
        assert steps == list(range(len(steps))) and steps


def test_dt_underflow_policies(scene):
    """An adaptive member whose dt collapses mirrors the sequential
    RuntimeError by default; 'retire' keeps the rest of the sweep alive."""
    system, members = scene
    sys2, _ = _ensemble_system()
    sys2.params = dataclasses.replace(system.params, fiber_error_tol=0.0,
                                      dt_min=1e-3)
    runner = EnsembleRunner(sys2, batch_impl="vmap")
    bad = [MemberSpec("bad", members[0].state, t_final=0.1)]
    with pytest.raises(RuntimeError, match="smaller than dt_min"):
        _drain(runner, bad, batch=1)
    _, records, retired, _ = _drain(runner, bad, batch=1,
                                    on_dt_underflow="retire")
    assert retired == ["bad"]
    assert any(r["event"] == "dt_underflow" for r in records)


def test_nan_lane_isolation_bitwise(scene, runners):
    """ISSUE-9 satellite pin — the seed behavior skelly-guard's quarantine
    builds on: NaN injected into one lane's state leaves every SIBLING
    lane's trajectory bitwise unchanged (frozen/failed lanes are masked
    selects, and batched row operations never mix members)."""
    from skellysim_tpu.guard import chaos, verdict

    _, members = scene
    runner = runners["vmap"]
    states = [m.state for m in members[:B_LANES]]
    ens = runner.make_ensemble(states, [0.004] * B_LANES)

    clean_rounds = []
    e = ens
    for _ in range(3):
        e, _ = runner.step(e)
        clean_rounds.append(e.states)

    e2 = chaos.poison_lane(ens, 0)
    info2 = None
    for i in range(3):
        e2, info2 = runner.step(e2)
        for la, lb in zip(jax.tree_util.tree_leaves(clean_rounds[i]),
                          jax.tree_util.tree_leaves(e2.states)):
            a, b = np.asarray(la), np.asarray(lb)
            assert np.array_equal(a[1:], b[1:], equal_nan=True), \
                "sibling lane perturbed by a poisoned neighbor"
    health = np.asarray(info2.health)
    failed = np.asarray(info2.failed)
    assert health[0] & verdict.NONFINITE and bool(failed[0])
    assert not failed[1:].any() and not health[1:].any()


def test_failed_lane_quarantine_policies(scene, runners):
    """Terminal verdicts quarantine: on_failure='retire' retires JUST the
    poisoned member (reason 'failed', verdict attached) and the sweep
    completes; the default mirrors the sequential abort."""
    from skellysim_tpu.guard import chaos, verdict

    _, members = scene
    runner = runners["vmap"]
    events = []
    sched = EnsembleScheduler(runner, members[:2], B_LANES,
                              metrics=events.append, on_failure="retire")
    sched.ens = chaos.poison_lane(sched.ens, sched.lane_of("m0"))
    retired = sched.run()
    fails = [r for r in events if r.get("event") == "failed"]
    assert [f["member"] for f in fails] == ["m0"]
    assert fails[0]["health"] & verdict.NONFINITE
    assert fails[0]["verdict"] == "nonfinite"
    from skellysim_tpu.io.ensemble_io import ENSEMBLE_FAILURE_FIELDS

    assert set(fails[0]) == set(ENSEMBLE_FAILURE_FIELDS)
    assert "m1" in retired and "m0" in retired

    sched2 = EnsembleScheduler(runner, members[:2], B_LANES)
    sched2.ens = chaos.poison_lane(sched2.ens, sched2.lane_of("m0"))
    with pytest.raises(RuntimeError, match="terminal solver health"):
        sched2.run()


def test_degenerate_t_final_member_retires_instead_of_hanging(scene, runners):
    """A member seated at or past its t_final (degenerate swept value,
    resumed state beyond it) must retire unstepped — an inert occupied lane
    used to spin the drain loop forever."""
    _, members = scene
    degenerate = MemberSpec("done", members[0].state, t_final=0.0)
    live = MemberSpec("live", members[1].state, t_final=members[1].t_final)
    _, records, retired, sched = _drain(runners["vmap"], [degenerate, live],
                                        batch=2, max_rounds=50)
    assert sorted(retired) == ["done", "live"]
    done_steps = [r for r in records
                  if r["event"] == "step" and r["member"] == "done"]
    assert not done_steps
    assert sched.rounds < 50


def test_runner_rejects_untraceable_configs(scene):
    system, members = scene
    with pytest.raises(ValueError, match="batch_impl"):
        EnsembleRunner(system, batch_impl="pmap")
    ew = dataclasses.replace(system.params, pair_evaluator="ewald")
    sys_ew, _ = _ensemble_system()
    sys_ew.params = ew
    with pytest.raises(ValueError, match="ewald"):
        EnsembleRunner(sys_ew)
    # dynamic instability is no longer rejected (skelly-scenario runs it
    # in-trace) — but a member whose live fiber resolution does not match
    # dynamic_instability.n_nodes still fails loudly at assembly
    di = dataclasses.replace(
        system.params,
        dynamic_instability=dataclasses.replace(
            system.params.dynamic_instability, n_nodes=16))
    sys_di, _ = _ensemble_system()
    sys_di.params = di
    runner_di = EnsembleRunner(sys_di)
    assert runner_di.di_enabled
    with pytest.raises(ValueError, match="live *\n? *resolution|resolution"):
        runner_di.make_ensemble([members[0].state], [0.1],
                                rngs=[SimRNG(1).member(0)])


def test_stack_states_rejects_mismatched_members(scene):
    system, members = scene
    a = members[0].state
    wrong_shape = a._replace(fibers=a.fibers._replace(
        x=jnp.concatenate([a.fibers.x, a.fibers.x], axis=0)))
    with pytest.raises(ValueError, match="leaf"):
        stack_states([a, wrong_shape])
    wrong_dtype = jax.tree_util.tree_map(
        lambda l: l.astype(jnp.float32)
        if jnp.issubdtype(l.dtype, jnp.floating) else l, a)
    with pytest.raises(ValueError, match="dtype|leaf"):
        stack_states([a, wrong_dtype])


def test_gmres_vmap_masked_convergence():
    """solver/ pin: under vmap, a member that converges early keeps exactly
    its solo solution/iters while slower members keep iterating (the
    while_loop's select-masked carries); `lax`-only control flow is what
    makes the whole system step batchable."""
    from skellysim_tpu.solver import gmres

    rng = np.random.default_rng(11)
    n, B = 24, 3
    # member i's conditioning worsens with i -> strictly more iterations
    As, bs = [], []
    for i in range(B):
        Q = rng.standard_normal((n, n)) / np.sqrt(n)
        As.append(jnp.asarray(np.eye(n) + (0.1 + 0.4 * i) * Q))
        bs.append(jnp.asarray(rng.standard_normal(n)))
    As, bs = jnp.stack(As), jnp.stack(bs)

    def solve(A, b):
        return gmres(lambda v: A @ v, b, tol=1e-12, restart=30, maxiter=90)

    batched = jax.jit(jax.vmap(solve))(As, bs)
    solo = [solve(As[i], bs[i]) for i in range(B)]
    iters = [int(r.iters) for r in solo]
    assert len(set(iters)) > 1, "members must genuinely differ in iters"
    for i, r in enumerate(solo):
        assert int(batched.iters[i]) == iters[i]
        assert bool(batched.converged[i]) and bool(r.converged)
        np.testing.assert_allclose(np.asarray(batched.x[i]), np.asarray(r.x),
                                   rtol=1e-9, atol=1e-12)


def test_simrng_member_streams():
    """Satellite: deterministic per-member stream derivation — disjoint,
    scheduling-order independent, and dump/restore round-trippable."""
    base = SimRNG(seed=7)
    m0, m3 = base.member(0), base.member(3)
    # derivation ignores the base bundle's draw position
    base.shared.uniform(size=4)
    base.distributed.normal(size=4)
    assert base.member(3).distributed.dump() == m3.distributed.dump()
    # streams are disjoint across members and from the base bundle
    draws = {tuple(rng.distributed.uniform(size=3).tolist())
             for rng in (SimRNG(seed=7), m0, m3, base.member(1))}
    assert len(draws) == 4
    with pytest.raises(ValueError):
        base.member(-1)


def test_simrng_member_dump_restore_roundtrip():
    m = SimRNG(seed=13).member(5)
    m.shared.uniform(size=2)
    m.distributed.normal(size=3)
    dumped = m.dump_state()
    restored = SimRNG.from_state(dumped)
    assert restored.dump_state() == dumped
    np.testing.assert_array_equal(restored.distributed.uniform(size=8),
                                  m.distributed.uniform(size=8))
    np.testing.assert_array_equal(restored.shared.normal(size=8),
                                  m.shared.normal(size=8))
