"""Chebyshev spectral machinery + penalty fiber.

Oracles: numpy.polynomial.chebyshev for the spectral operators (the reference
validates against committed Julia results from the same formulas,
`unit_test_skelly_chebyshev.cpp`); structural identities (derivative of the
integral, reconstruction of known polynomials) for the integrated
representation; and physical behavior (clamped end pinned, deflection in
shear, near-inextensibility) for the Newton-evolved fiber
(`jnewton_fiberpenalty_test.cpp:34-80`).
"""

import jax.numpy as jnp
import numpy as np
import numpy.polynomial.chebyshev as npcheb

from skellysim_tpu.fibers import chebyshev as cheb
from skellysim_tpu.fibers import chebyshev_fiber as cf


# ------------------------------------------------------------- spectral ops

def test_chebyshev_points_are_reversed_gauss_nodes():
    n = 16
    pts = cheb.chebyshev_points(n)
    # same set as numpy's first-kind Gauss points, in ascending order
    np.testing.assert_allclose(pts, sorted(npcheb.chebpts1(n)), atol=1e-14)
    assert np.all(np.diff(pts) > 0)

    scaled = cheb.chebyshev_points(n, 0.0, 2.0)
    np.testing.assert_allclose(scaled, pts + 1.0, atol=1e-14)


def test_vandermonde_matches_numpy_chebvander():
    n = 12
    x = cheb.chebyshev_points(n)
    np.testing.assert_allclose(cheb.vander(x, n - 1), npcheb.chebvander(x, n - 1),
                               atol=1e-13)
    np.testing.assert_allclose(cheb.vandermonde(n) @ cheb.inverse_vandermonde(n),
                               np.eye(n), atol=1e-10)


def test_derivative_coeffs_match_numpy_chebder():
    rng = np.random.default_rng(3)
    for size in (2, 5, 9, 16):
        p = rng.standard_normal(size)
        np.testing.assert_allclose(cheb.derivative_coeffs(p),
                                   npcheb.chebder(p), atol=1e-12)


def test_derivative_matrix_differentiates():
    n = 14
    rng = np.random.default_rng(5)
    p = rng.standard_normal(n)
    D1 = cheb.derivative_matrix(n, 1)
    np.testing.assert_allclose(D1 @ p, npcheb.chebder(p), atol=1e-11)
    D2 = cheb.derivative_matrix(n, 2)
    np.testing.assert_allclose(D2 @ p, npcheb.chebder(p, 2), atol=1e-10)
    # scale factor applies per derivative order
    D2s = cheb.derivative_matrix(n, 2, scale_factor=3.0)
    np.testing.assert_allclose(D2s @ p, 9.0 * npcheb.chebder(p, 2), atol=1e-9)


def test_integration_matrix_inverts_derivative():
    n = 12
    rng = np.random.default_rng(7)
    p = rng.standard_normal(n)
    IM = cheb.integration_matrix(n)
    D1 = cheb.derivative_matrix(n, 1)
    # d/dx of the antiderivative recovers the series (up to truncation)
    q = IM @ p
    np.testing.assert_allclose(D1 @ q, p[:-1], atol=1e-10)
    # the IntegrationMatrix construction pins the value at x = -1 via its
    # bottom input row; the value row of the inverse reproduces it
    np.testing.assert_allclose(npcheb.chebval(-1.0, q), p[-1], atol=1e-10)


def test_multiply_matches_numpy_chebmul():
    rng = np.random.default_rng(9)
    a, b = rng.standard_normal(6), rng.standard_normal(6)
    full = npcheb.chebmul(a, b)
    got = np.asarray(cheb.multiply(jnp.asarray(a), jnp.asarray(b), "c", "c", "c",
                                   n_out=11, nm=16))
    np.testing.assert_allclose(got, full, atol=1e-12)


def test_evalpoly_clenshaw():
    rng = np.random.default_rng(11)
    p = rng.standard_normal(8)
    for x in (-1.0, -0.3, 0.5, 1.0):
        np.testing.assert_allclose(float(cheb.evalpoly(x, jnp.asarray(p))),
                                   npcheb.chebval(x, p), atol=1e-12)


# ---------------------------------------------- integrated representation

def test_divide_and_construct_derivative_chain():
    """The constructed caches satisfy d/ds X^(k) = X^(k+1) with the [0, L]
    arclength scaling."""
    N, L = 16, 2.0
    solver = cf.FiberSolverChebyshevPenalty(N, N - 2, N - 4, N - 6)
    rng = np.random.default_rng(13)
    XX = jnp.asarray(rng.standard_normal(solver.solution_size))
    div = solver.divide_and_construct(XX, L)

    scale = 2.0 / L  # d/ds = (2/L) d/dx on the mapped domain
    for lo, hi in [(div.XC, div.XsC), (div.XsC, div.XssC),
                   (div.XssC, div.XsssC), (div.XsssC, div.XssssC),
                   (div.YC, div.YsC), (div.TC, div.TsC), (div.TsC, div.TssC)]:
        D = cheb.derivative_matrix(lo.shape[0], 1, scale_factor=scale)
        np.testing.assert_allclose(np.asarray(D @ lo),
                                   np.asarray(hi)[:lo.shape[0] - 1], atol=1e-9)


def test_initial_state_is_straight_vertical_fiber():
    N, L = 20, 1.0
    solver, XX = cf.setup_solver_initialstate(N, L)
    x, y = cf.node_positions(solver, XX, L)
    np.testing.assert_allclose(np.asarray(x), 0.0, atol=1e-12)
    # y runs over [0, L] along the arclength nodes
    np.testing.assert_allclose(np.asarray(y),
                               cheb.chebyshev_points(N - 4, 0.0, L), atol=1e-10)
    err = float(cf.extensibility_error(solver, XX, L))
    assert err < 1e-12


# ------------------------------------------------------- Newton + evolution

def test_newton_shear_evolution():
    """Single-Newton backward Euler in shear flow: the clamped end stays
    pinned with vertical director, the free end deflects downstream, and the
    penalty keeps the fiber nearly inextensible
    (`jnewton_fiberpenalty_test.cpp:68-120` behavior)."""
    N, L, zeta, dt = 20, 1.0, 1.0, 0.005
    solver, XX = cf.setup_solver_initialstate(N, L)

    final, ext_errors = cf.evolve(solver, XX, L=L, zeta=zeta, dt=dt, n_steps=20)
    div = solver.divide_and_construct(final, L)

    # clamp: x(0) = y(0) = 0, (xs, ys)(0) = (0, 1)
    assert abs(float(cheb.left_eval(div.XC))) < 1e-8
    assert abs(float(cheb.left_eval(div.YC))) < 1e-8
    assert abs(float(cheb.left_eval(div.XsC))) < 1e-6
    assert abs(float(cheb.left_eval(div.YsC)) - 1.0) < 1e-6

    # shear pushes the free end in +x; the tip still sits near height L
    assert float(cheb.right_eval(div.XC)) > 1e-3
    assert float(cheb.right_eval(div.YC)) > 0.9 * L

    # penalty inextensibility
    assert float(ext_errors[-1]) < 5e-2
    assert np.all(np.isfinite(np.asarray(final)))


def test_single_newton_step_solves_linearized_system_exactly():
    """The penalty objective pairs every current-state factor with old-state
    coefficients, so it is linear in XX and one Newton step lands at machine
    precision — the property the reference's single-Newton backward Euler
    (`jnewton_fiberpenalty_test.cpp:55-66`) relies on."""
    N, L, zeta, dt = 16, 1.0, 0.5, 0.01
    solver, XX = cf.setup_solver_initialstate(N, L)

    old = XX
    r0 = np.abs(np.asarray(
        cf.sheer_deflection_objective(XX, solver, old, L, zeta, dt))).max()
    x1 = cf.newton_step(solver, XX, old, L, zeta, dt)
    r1 = np.abs(np.asarray(
        cf.sheer_deflection_objective(x1, solver, old, L, zeta, dt))).max()
    assert r0 > 1e-6      # the un-updated state does not satisfy the step
    assert r1 < 1e-10     # one Newton solve does, exactly
