"""Physics oracles from the reference's combined test suite.

Three independent checks that stress the BC-row surgery and fiber-fiber
hydrodynamic coupling (`fd_fiber.py:130-231`) in ways the rest of the suite
does not:

* fiber under constant tangential motor force vs the slender-body drag
  gamma = -4 pi L eta / ln(e eps^2)
  (`/root/reference/tests/combined/test_fiber_const_force.py:40-77`, 1e-6)
* two-filament interaction: a perturbed driven filament deflects its straight
  neighbor purely through hydrodynamics; final tip positions vs the
  reference's committed regression values
  (`/root/reference/tests/combined/test_fiber_dualfilament.py:50-76`)
* clamped Euler buckling at sigma = 72 vs 80: below the second critical
  compression the kicked oscillation decays, above it grows
  (`/root/reference/tests/combined/test_clamped_buckling_sigma72.py`,
  `test_clamped_buckling_sigma80.py`)
"""

import dataclasses

import jax.numpy as jnp
import numpy as np
import pytest

from skellysim_tpu.config.schema import perturbed_fiber_positions
from skellysim_tpu.fibers import container as fc
from skellysim_tpu.params import Params
from skellysim_tpu.system import System
from skellysim_tpu.system.sources import PointSources


def _straight_fiber(n_nodes, length, origin, direction, **kw):
    t = np.linspace(0.0, length, n_nodes)
    x = np.asarray(origin, dtype=float)[None, :] \
        + t[:, None] * np.asarray(direction, dtype=float)[None, :]
    return x


def test_fiber_const_force_sbt_drag():
    """Free fiber with tangential motor force translates at F/gamma with
    gamma the SBT parallel drag; reference gate 1e-6
    (`test_fiber_const_force.py:40-77`)."""
    eta, length, force_scale, n_nodes, radius = 0.7, 0.75, 0.31, 8, 0.0125
    x = _straight_fiber(n_nodes, length, [0, 0, 0], [0, 0, 1])
    fibers = fc.make_group(x[None], lengths=length, bending_rigidity=0.0025,
                           radius=radius, force_scale=force_scale,
                           dtype=jnp.float64)
    params = Params(eta=eta, dt_initial=1e-4, dt_write=1e-3, t_final=5e-3,
                    gmres_tol=1e-10, adaptive_timestep_flag=False)
    system = System(params)
    state = system.make_state(fibers=fibers)

    x0 = np.asarray(state.fibers.x[0, 0])
    t0 = float(state.time)
    state = system.run(state)
    xf = np.asarray(state.fibers.x[0, 0])
    tf = float(state.time)

    v = (xf - x0) / (tf - t0)
    epsilon = radius / length
    gamma = force_scale * length / v[2]
    gamma_theory = -4 * np.pi * length * eta / np.log(np.e * epsilon**2)
    rel = abs(1 - gamma / gamma_theory)
    assert rel < 1e-6, rel


@pytest.mark.slow  # 39s on the 2-core box: heavy in-process integration (fast-tier budget)
def test_fiber_dualfilament_deflection():
    """A perturbed compressed filament drives its straight neighbor through
    hydrodynamics alone; final tip x-positions vs the reference's committed
    values (`test_fiber_dualfilament.py:60-64`).

    The committed values are the reference implementation's own golden output
    at these parameters (x0=-0.004765810967995735, x1=1.0048647877439878).
    Measured cross-implementation agreement is ~1e-10 relative — the FD
    fiber discretization, BC rows, and fiber-fiber hydrodynamics are
    numerically equivalent to the reference's — so the gate here is the
    reference's own 1e-6.
    """
    sigma, length, E, n_nodes = 0.0225, 2.0, 0.0025, 64
    x_pert = perturbed_fiber_positions(0.01, length, np.array([0.0, 0.0, 0.0]),
                                       np.array([0.0, 0.0, 1.0]), n_nodes,
                                       ortho=np.array([1.0, 0.0, 0.0]))
    x_straight = _straight_fiber(n_nodes, length, [1.0, 0, 0], [0, 0, 1])
    fibers = fc.make_group(np.stack([x_pert, x_straight]), lengths=length,
                           bending_rigidity=E, radius=0.0125,
                           force_scale=-sigma, minus_clamped=True,
                           dtype=jnp.float64)
    params = Params(eta=1.0, dt_initial=0.1, t_final=10.0, gmres_tol=1e-10,
                    adaptive_timestep_flag=False)
    system = System(params)
    state = system.make_state(fibers=fibers)
    state = system.run(state)

    x0 = float(state.fibers.x[0, -1, 0])   # driver tip deflection
    x1 = float(state.fibers.x[1, -1, 0])   # hydrodynamic response tip
    x0_ref = -0.004765810967995735
    x1_ref = 1.0048647877439878
    rel = np.hypot(abs(1 - x0 / x0_ref), abs(1 - x1 / x1_ref))
    # both fibers moved the right way (driver bent -x, neighbor pushed +x)
    assert x0 < 0 and x1 > 1.0
    assert rel < 1e-6, (x0, x1, rel)  # the reference's own regression gate


def _buckling_deflections(sigma, t_final=50.0):
    """Clamped fiber under compressive motor force, kicked sideways by a
    transient point force; returns the tip x-deflection time series
    (`test_clamped_buckling_sigma72.py:13-55`)."""
    length, E, n_nodes = 1.0, 0.0025, 32
    force_scale = -sigma * E / length**3
    x = _straight_fiber(n_nodes, length, [0, 0, 0], [0, 0, 1])
    fibers = fc.make_group(x[None], lengths=length, bending_rigidity=E,
                           radius=0.0125, force_scale=force_scale,
                           minus_clamped=True, dtype=jnp.float64)
    points = PointSources.make(position=[[0.0, 0.0, 10 * length]],
                               force=[[10.0, 0.0, 0.0]], time_to_live=1.0,
                               dtype=jnp.float64)
    params = Params(eta=1.0, dt_initial=0.02, dt_min=0.01, dt_max=0.1,
                    dt_write=0.1, t_final=t_final, gmres_tol=1e-10,
                    adaptive_timestep_flag=True)
    system = System(params)
    state = system.make_state(fibers=fibers, points=points)

    tip_x = []
    state = system.run(state, writer=lambda s, sol: tip_x.append(
        float(s.fibers.x[0, -1, 0])))
    return np.array(tip_x)


def _oscillation_peaks(x):
    """Indices of local maxima with positive height (scipy-free find_peaks)."""
    up = (x[1:-1] > x[:-2]) & (x[1:-1] >= x[2:]) & (x[1:-1] > 0)
    return np.nonzero(up)[0] + 1


@pytest.mark.slow
def test_clamped_buckling_sigma72_decays():
    """sigma=72 sits below the second critical compression: the kicked
    oscillation decays peak to peak (`test_clamped_buckling_sigma72.py:57-77`,
    committed peaks 0.08844356 / 0.05563314)."""
    x = _buckling_deflections(72.0)
    peaks = _oscillation_peaks(x)
    assert len(peaks) >= 3, "expected at least 3 oscillation peaks"
    # ignore the first peak (the kick itself)
    assert x[peaks[2]] < x[peaks[1]]


@pytest.mark.slow
def test_clamped_buckling_sigma80_grows():
    """sigma=80 is supercritical: the oscillation amplitude grows
    (`test_clamped_buckling_sigma80.py`: x_peak2 > x_peak1 with committed
    peaks starting at 0.09575812)."""
    x = _buckling_deflections(80.0)
    peaks = _oscillation_peaks(x)
    assert len(peaks) >= 3
    assert x[peaks[2]] > x[peaks[1]]
