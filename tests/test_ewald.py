"""Spectral Ewald evaluator vs the dense kernel oracle.

The evaluator replaces the reference's FMM slot (`include/kernels.hpp:56-134`)
with a TPU-native near/far split: every stage here is pinned against either a
closed form or the dense `kernels.stokeslet_direct` sum.
"""

import math

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from skellysim_tpu.ops import ewald, kernels


def _cloud(n, seed=3, box=3.0):
    rng = np.random.default_rng(seed)
    pts = jnp.asarray(rng.uniform(-box, box, (n, 3)))
    f = jnp.asarray(rng.standard_normal((n, 3)))
    return pts, f


def test_split_identity_exact():
    """G_near + G_far == G pointwise (closed forms; machine epsilon)."""
    rng = np.random.default_rng(0)
    eta, xi = 1.3, 1.7
    d = jnp.asarray(rng.uniform(-3, 3, (200, 3)))
    G_far = np.asarray(ewald.g_far_pair(d, xi, eta))
    G_near = np.zeros((200, 3, 3))
    for k in range(3):
        e = jnp.zeros((1, 3)).at[0, k].set(1.0)
        G_near[:, :, k] = np.asarray(
            ewald.stokeslet_near_block(d, jnp.zeros((1, 3)), e, xi)
        ) / (8 * np.pi * eta)
    r = np.linalg.norm(np.asarray(d), axis=1)
    rhat = np.asarray(d) / r[:, None]
    G = (np.eye(3)[None] / r[:, None, None]
         + rhat[:, :, None] * rhat[:, None, :] / r[:, None, None]) \
        / (8 * np.pi * eta)
    assert np.abs(G_near + G_far - G).max() < 1e-15


def test_near_field_decays_past_cutoff():
    eta, xi = 1.0, 2.0
    d = jnp.asarray([[4.5 / 2.0, 0.0, 0.0]])  # r = 4.5/xi
    e = jnp.zeros((1, 3)).at[0, 0].set(1.0)
    u = np.asarray(ewald.stokeslet_near_block(d, jnp.zeros((1, 3)), e, xi))
    assert np.abs(u).max() / (8 * np.pi * eta) < 1e-9


def test_kspace_multiplier_matches_analytic_far_field():
    """Direct lattice k-sum of -(k^2 I - kk^T) Bhat == G_far (no windows)."""
    eta, xi, D = 1.3, 2.0, 3.0
    tol = 1e-9
    c = math.sqrt(math.log(1 / tol)) + 3.0
    R = D + c / xi
    L = D + R + 4.0 / xi
    kmax = 2 * xi * math.sqrt(math.log(1 / tol) + 4)
    M = int(np.ceil(kmax * L / np.pi)) + 1
    k1 = 2 * np.pi * np.fft.fftfreq(M, d=L / M)
    KX, KY, KZ = np.meshgrid(k1, k1, k1, indexing="ij")
    K2 = KX**2 + KY**2 + KZ**2
    Bhat = np.asarray(ewald.bhat_far_trunc(jnp.asarray(np.sqrt(K2)), xi, R))

    rng = np.random.default_rng(1)
    f = rng.standard_normal(3)
    for _ in range(3):
        d = rng.uniform(-D / math.sqrt(3), D / math.sqrt(3), 3)
        phase = np.exp(1j * (KX * d[0] + KY * d[1] + KZ * d[2]))
        kdotf = KX * f[0] + KY * f[1] + KZ * f[2]
        u = np.stack([(K2 * f[0] - KX * kdotf),
                      (K2 * f[1] - KY * kdotf),
                      (K2 * f[2] - KZ * kdotf)]) * Bhat * phase
        u = -u.sum(axis=(1, 2, 3)).real / (L**3) / (8 * np.pi * eta)
        ref = np.asarray(ewald.g_far_pair(jnp.asarray(d)[None], xi, eta))[0] @ f
        assert np.linalg.norm(u - ref) / np.linalg.norm(ref) < 3e-8


def test_ewald_matches_dense_low_tol():
    pts, f = _cloud(400)
    plan = ewald.plan_ewald(np.asarray(pts), eta=1.3, tol=1e-4)
    u = np.asarray(ewald.stokeslet_ewald(plan, pts, pts, f))
    ref = np.asarray(kernels.stokeslet_direct(pts, pts, f, 1.3))
    rel = np.linalg.norm(u - ref) / np.linalg.norm(ref)
    assert rel < 1e-3, rel


def test_ewald_matches_dense_high_tol():
    pts, f = _cloud(400, seed=5)
    plan = ewald.plan_ewald(np.asarray(pts), eta=0.9, tol=1e-7)
    u = np.asarray(ewald.stokeslet_ewald(plan, pts, pts, f))
    ref = np.asarray(kernels.stokeslet_direct(pts, pts, f, 0.9))
    rel = np.linalg.norm(u - ref) / np.linalg.norm(ref)
    assert rel < 3e-6, rel


def test_ewald_disjoint_targets():
    """Velocity-field evaluation: targets distinct from sources, no self term."""
    pts, f = _cloud(300, seed=7)
    rng = np.random.default_rng(8)
    trg = jnp.asarray(rng.uniform(-3, 3, (111, 3)))
    plan = ewald.plan_ewald(np.vstack([np.asarray(pts), np.asarray(trg)]),
                            eta=1.0, tol=1e-6)
    u = np.asarray(ewald.stokeslet_ewald(plan, pts, trg, f, n_self=0))
    ref = np.asarray(kernels.stokeslet_direct(pts, trg, f, 1.0))
    rel = np.linalg.norm(u - ref) / np.linalg.norm(ref)
    assert rel < 1e-5, rel


def test_ewald_clustered_fiber_geometry():
    """Fiber-like clustering (dense lines, empty space) — the production
    occupancy pattern, exercising bucket padding and cell capacity."""
    rng = np.random.default_rng(11)
    n_fib, n_nodes = 24, 24
    origins = rng.uniform(-2, 2, (n_fib, 3))
    dirs = rng.normal(size=(n_fib, 3))
    dirs /= np.linalg.norm(dirs, axis=1, keepdims=True)
    t = np.linspace(0, 1.0, n_nodes)
    pts = (origins[:, None, :] + t[None, :, None] * dirs[:, None, :]
           ).reshape(-1, 3)
    pts = jnp.asarray(pts)
    f = jnp.asarray(rng.standard_normal((len(pts), 3)))
    plan = ewald.plan_ewald(np.asarray(pts), eta=1.0, tol=1e-6)
    u = np.asarray(ewald.stokeslet_ewald(plan, pts, pts, f))
    ref = np.asarray(kernels.stokeslet_direct(pts, pts, f, 1.0))
    rel = np.linalg.norm(u - ref) / np.linalg.norm(ref)
    assert rel < 1e-5, rel


def test_ewald_f32_mode():
    """f32 arrays (the TPU throughput tier) keep ~1e-4-class accuracy."""
    pts64, f64 = _cloud(400, seed=13)
    plan = ewald.plan_ewald(np.asarray(pts64), eta=1.0, tol=1e-4)
    pts, f = pts64.astype(jnp.float32), f64.astype(jnp.float32)
    u = np.asarray(ewald.stokeslet_ewald(plan, pts, pts, f))
    assert u.dtype == np.float32
    ref = np.asarray(kernels.stokeslet_direct(pts64, pts64, f64, 1.0))
    rel = np.linalg.norm(u - ref) / np.linalg.norm(ref)
    assert rel < 3e-3, rel


def test_plan_stable_under_drift():
    """Small point drift must reuse the same compiled program: every plan
    field except the (traced) anchors is identical."""
    pts, _ = _cloud(500, seed=17)
    p1 = ewald.plan_ewald(np.asarray(pts), eta=1.0, tol=1e-5)
    drift = np.asarray(pts) + 0.003 * np.random.default_rng(1).standard_normal(
        (500, 3))
    p2 = ewald.plan_ewald(drift, eta=1.0, tol=1e-5)
    k1 = ewald.strip_anchors(p1)
    k2 = ewald.strip_anchors(p2)
    assert k1 == k2
    assert hash(k1) == hash(k2)
    # anchor hops stay on the cell lattice (partition-preserving)
    step = p1.cell_size
    for plan_pair in ((p1.box_lo, p2.box_lo), (p1.cell_lo, p2.cell_lo)):
        for a, b in zip(*plan_pair):
            assert abs((a - b) / step - round((a - b) / step)) < 1e-9


def test_ewald_mixed_target_set():
    """The coupled-matvec layout: targets = [sources | shell/body nodes],
    self terms dropped only for the leading coincident block."""
    pts, f = _cloud(300, seed=19)
    rng = np.random.default_rng(20)
    extra = jnp.asarray(rng.uniform(-3, 3, (77, 3)))
    trg = jnp.concatenate([pts, extra], axis=0)
    plan = ewald.plan_ewald(np.asarray(trg), eta=1.1, tol=1e-6)
    u = np.asarray(ewald.stokeslet_ewald(plan, pts, trg, f,
                                         n_self=pts.shape[0]))
    ref_self = np.asarray(kernels.stokeslet_direct(pts, pts, f, 1.1))
    ref_extra = np.asarray(kernels.stokeslet_direct(pts, extra, f, 1.1))
    ref = np.vstack([ref_self, ref_extra])
    rel = np.linalg.norm(u - ref) / np.linalg.norm(ref)
    assert rel < 1e-5, rel


@pytest.mark.slow  # heavy coupled-solve integration; sibling fast tests keep the seam covered (ISSUE-9 870s-budget re-triage)
def test_system_solve_with_ewald_evaluator():
    """pair_evaluator="ewald": the coupled implicit solve matches the direct
    evaluator's solution to the Ewald tolerance."""
    import dataclasses

    from skellysim_tpu.fibers import container as fc
    from skellysim_tpu.params import Params
    from skellysim_tpu.system import BackgroundFlow, System

    rng = np.random.default_rng(23)
    n_fib, n_nodes = 12, 16
    origins = rng.uniform(-2, 2, (n_fib, 3))
    dirs = rng.normal(size=(n_fib, 3))
    dirs /= np.linalg.norm(dirs, axis=1, keepdims=True)
    t = np.linspace(0, 1.0, n_nodes)
    x = origins[:, None, :] + t[None, :, None] * dirs[:, None, :]

    base = Params(eta=1.0, dt_initial=1e-3, t_final=1e-2, gmres_tol=1e-8,
                  adaptive_timestep_flag=False, ewald_tol=1e-8)
    sols = {}
    for ev in ("direct", "ewald"):
        params = dataclasses.replace(base, pair_evaluator=ev)
        system = System(params)
        fibers = fc.make_group(x, lengths=1.0, bending_rigidity=0.01,
                               radius=0.0125)
        state = system.make_state(
            fibers=fibers,
            background=BackgroundFlow.make(uniform=(1.0, 0.0, 0.0)))
        _, solution, info = system.step(state)
        assert bool(info.converged), ev
        sols[ev] = np.asarray(solution)
    err = (np.linalg.norm(sols["ewald"] - sols["direct"])
           / np.linalg.norm(sols["direct"]))
    assert err < 1e-6, err


@pytest.mark.slow  # 23s on the 2-core box (~45s+ 1-core-calibrated): heavy in-process integration (fast-tier budget)
def test_ewald_with_inactive_padding_fibers():
    """grow_capacity padding (inactive slots replicating slot 0) must not
    blow up bucket occupancy or change results: padded sources are spread
    over the cell region with zero strength."""
    import dataclasses

    from skellysim_tpu.fibers import container as fc
    from skellysim_tpu.params import Params
    from skellysim_tpu.system import BackgroundFlow, System

    rng = np.random.default_rng(29)
    n_fib, n_nodes = 8, 16
    origins = rng.uniform(-2, 2, (n_fib, 3))
    dirs = rng.normal(size=(n_fib, 3))
    dirs /= np.linalg.norm(dirs, axis=1, keepdims=True)
    t = np.linspace(0, 1.0, n_nodes)
    x = origins[:, None, :] + t[None, :, None] * dirs[:, None, :]

    params = Params(eta=1.0, dt_initial=1e-3, t_final=1e-2, gmres_tol=1e-8,
                    pair_evaluator="ewald", ewald_tol=1e-7,
                    adaptive_timestep_flag=False)
    system = System(params)

    fibers = fc.make_group(x, lengths=1.0, bending_rigidity=0.01,
                           radius=0.0125)
    state = system.make_state(
        fibers=fibers,
        background=BackgroundFlow.make(uniform=(1.0, 0.0, 0.0)))
    _, sol_ref, info_ref = system.step(state)
    assert bool(info_ref.converged)

    grown = fc.grow_capacity(fibers, 3 * n_fib)   # 2/3 inactive padding
    state_g = system.make_state(
        fibers=grown,
        background=BackgroundFlow.make(uniform=(1.0, 0.0, 0.0)))
    # plan reserves fill capacity for the inactive nodes, not one hot cell
    plan = system.make_ewald_plan(state_g)
    assert plan.max_occ <= 4 * system.make_ewald_plan(state).max_occ
    new_g, sol_g, info_g = system.step(state_g)
    assert bool(info_g.converged)
    n_active = n_fib * 4 * n_nodes
    err = (np.linalg.norm(np.asarray(sol_g)[:n_active] - np.asarray(sol_ref))
           / np.linalg.norm(np.asarray(sol_ref)))
    assert err < 1e-6, err


def test_ewald_anchor_hop_reuses_compiled_program():
    """A pure translation of the cloud (anchor hop) must not retrace the
    jitted evaluator: the anchors are traced operands."""
    from skellysim_tpu.ops.ewald import _stokeslet_ewald_impl

    pts, f = _cloud(200, seed=31)
    plan1 = ewald.plan_ewald(np.asarray(pts), eta=1.0, tol=1e-5)
    u1 = ewald.stokeslet_ewald(plan1, pts, pts, f)
    n_compiled = _stokeslet_ewald_impl._cache_size()
    shift = jnp.asarray([5.0 * plan1.cell_size, 0.0, 0.0])
    pts2 = pts + shift
    plan2 = ewald.plan_ewald(np.asarray(pts2), eta=1.0, tol=1e-5)
    u2 = ewald.stokeslet_ewald(plan2, pts2, pts2, f)
    assert _stokeslet_ewald_impl._cache_size() == n_compiled, \
        "anchor hop forced a recompile"
    # translation invariance of the physics
    np.testing.assert_allclose(np.asarray(u2), np.asarray(u1),
                               rtol=0, atol=1e-8)


@pytest.mark.slow  # 30s on the 2-core box (~60s 1-core-calibrated): heavy in-process integration (fast-tier budget)
def test_block_sparse_near_field_on_fiber_cloud():
    """Line-clustered clouds auto-select the block-sparse near field
    (no occupancy padding waste); it agrees with the cells mode and the
    dense oracle."""
    import dataclasses

    rng = np.random.default_rng(43)
    nf, nn = 60, 64
    origins = rng.uniform(-5, 5, (nf, 3))
    dirs = rng.normal(size=(nf, 3))
    dirs /= np.linalg.norm(dirs, axis=1, keepdims=True)
    t = np.linspace(0, 1, nn)
    pts = jnp.asarray((origins[:, None, :]
                       + t[None, :, None] * dirs[:, None, :]).reshape(-1, 3))
    f = jnp.asarray(rng.standard_normal((len(pts), 3)))

    plan = ewald.plan_ewald(np.asarray(pts), eta=1.0, tol=1e-5)
    assert plan.near_mode == "blocks", (plan.near_mode, plan.max_occ)
    assert plan.K >= 8
    u = np.asarray(ewald.stokeslet_ewald(plan, pts, pts, f))

    sub = rng.choice(len(pts), 256, replace=False)
    ref = np.asarray(kernels.stokeslet_direct(
        pts, jnp.asarray(np.asarray(pts)[sub]), f, 1.0))
    rel = np.linalg.norm(u[sub] - ref) / np.linalg.norm(ref)
    assert rel < 1e-4, rel

    plan_c = dataclasses.replace(plan, near_mode="cells")
    uc = np.asarray(ewald.stokeslet_ewald(plan_c, pts, pts, f))
    agree = np.abs(u - uc).max()
    assert agree < 1e-5, agree


@pytest.mark.slow
def test_blocks_plan_probe_targets_fall_back_to_cells():
    """Disjoint probe targets on a blocks-mode plan must not lose near-field
    pairs to partition misalignment (reviewer-reproduced failure: a probe
    block straddling plan-unseen boundaries out-counts K). Probe calls take
    the cells path; accuracy must hold at probes sitting right against
    fibers."""
    rng = np.random.default_rng(47)
    nf, nn = 60, 64
    origins = rng.uniform(-5, 5, (nf, 3))
    dirs = rng.normal(size=(nf, 3))
    dirs /= np.linalg.norm(dirs, axis=1, keepdims=True)
    t = np.linspace(0, 1, nn)
    pts_np = (origins[:, None, :]
              + t[None, :, None] * dirs[:, None, :]).reshape(-1, 3)
    pts = jnp.asarray(pts_np)
    f = jnp.asarray(rng.standard_normal((len(pts), 3)))
    # probes hugging fiber nodes (worst case for dropped near pairs)
    probes = jnp.asarray(pts_np[rng.choice(len(pts_np), 200, replace=False)]
                         + 0.01 * rng.standard_normal((200, 3)))
    plan = ewald.plan_ewald(np.vstack([pts_np, np.asarray(probes)]),
                            eta=1.0, tol=1e-5, n_src=len(pts_np))
    assert plan.near_mode == "blocks"
    u = np.asarray(ewald.stokeslet_ewald(plan, pts, probes, f, n_self=0))
    ref = np.asarray(kernels.stokeslet_direct(pts, probes, f, 1.0))
    rel = np.linalg.norm(u - ref) / np.linalg.norm(ref)
    assert rel < 1e-4, rel


def test_stresslet_ewald_matches_dense():
    """Double-layer (stresslet) spectral Ewald vs the dense kernel. The
    double-layer multiplier carries one extra power of k, so achieved error
    runs ~10-60x the Stokeslet-calibrated tol — plan a correspondingly
    tighter tol for double-layer accuracy targets."""
    rng = np.random.default_rng(53)
    pts = jnp.asarray(rng.uniform(-3, 3, (400, 3)))
    S = jnp.asarray(rng.standard_normal((400, 3, 3)))
    plan = ewald.plan_ewald(np.asarray(pts), eta=1.3, tol=1e-5)
    u = np.asarray(ewald.stresslet_ewald(plan, pts, pts, S))
    ref = np.asarray(kernels.stresslet_direct(pts, pts, S, 1.3))
    rel = np.linalg.norm(u - ref) / np.linalg.norm(ref)
    assert rel < 1e-3, rel

    plan8 = ewald.plan_ewald(np.asarray(pts), eta=1.3, tol=1e-8)
    u8 = np.asarray(ewald.stresslet_ewald(plan8, pts, pts, S))
    rel8 = np.linalg.norm(u8 - ref) / np.linalg.norm(ref)
    assert rel8 < 5e-6, rel8


def test_stresslet_ewald_disjoint_targets():
    rng = np.random.default_rng(57)
    pts = jnp.asarray(rng.uniform(-3, 3, (300, 3)))
    S = jnp.asarray(rng.standard_normal((300, 3, 3)))
    trg = jnp.asarray(rng.uniform(-3, 3, (77, 3)))
    plan = ewald.plan_ewald(np.vstack([np.asarray(pts), np.asarray(trg)]),
                            eta=1.0, tol=1e-6)
    u = np.asarray(ewald.stresslet_ewald(plan, pts, trg, S))
    ref = np.asarray(kernels.stresslet_direct(pts, trg, S, 1.0))
    rel = np.linalg.norm(u - ref) / np.linalg.norm(ref)
    assert rel < 1e-4, rel


def test_stresslet_near_far_split_identity():
    """Closed-form screened stresslet split: near + far == exact, and the
    near part decays past the cutoff."""
    rng = np.random.default_rng(59)
    xi, eta = 1.9, 1.0
    src = jnp.zeros((1, 3))
    S = jnp.asarray(rng.standard_normal((1, 3, 3)))
    d = jnp.asarray(rng.uniform(-2.5, 2.5, (200, 3)))
    exact = np.asarray(kernels.stresslet_direct(src, d, S, eta))
    near = np.asarray(ewald.stresslet_near_block_ewald(d, src, S, xi)) \
        / (8 * np.pi * eta)
    r = np.linalg.norm(np.asarray(d), axis=1)
    assert np.abs(near[r > 4.5 / xi]).max() < 1e-8
    # far must be smooth through r -> 0: evaluate along a ray approaching the
    # source; a smooth odd kernel's magnitude must DECREASE toward zero
    ray = jnp.asarray(np.outer([0.3, 0.1, 0.03, 0.01], [1.0, 0.5, -0.2]))
    ex_r = np.asarray(kernels.stresslet_direct(src, ray, S, eta))
    nr_r = np.asarray(ewald.stresslet_near_block_ewald(ray, src, S, xi)) \
        / (8 * np.pi * eta)
    far_r = np.linalg.norm(ex_r - nr_r, axis=1)
    assert far_r[-1] < far_r[0]
    assert far_r[-1] < 0.05 * np.linalg.norm(np.asarray(S))


def _coupled_ewald_scene(dtype, n_fib=6, n_nodes=16):
    """Fibers + spherical shell + one body, the full coupled layout."""
    import jax.numpy as jnp

    from skellysim_tpu.fibers import container as fc
    from skellysim_tpu.testing import make_coupled_parts

    shell, shape, bodies = make_coupled_parts(192, 96, dtype)
    rng = np.random.default_rng(71)
    origins = rng.uniform(-2, 2, (n_fib, 3))
    dirs = rng.normal(size=(n_fib, 3))
    dirs /= np.linalg.norm(dirs, axis=1, keepdims=True)
    t = np.linspace(0, 1.0, n_nodes)
    x = origins[:, None, :] + t[None, :, None] * dirs[:, None, :]
    fibers = fc.make_group(x, lengths=1.0, bending_rigidity=0.01,
                           radius=0.0125, dtype=dtype)
    return fibers, shell, shape, bodies


@pytest.mark.slow
def test_coupled_solve_shell_body_through_ewald():
    """The full one-evaluator-serves-all seam (`include/kernels.hpp:56-134`,
    `periphery.cpp:337-352`, `body_container.cpp:552-573`): with
    ewald_min_sources=0 the shell AND body double-layer flows route through
    the spectral-Ewald stresslet inside the solve, and the converged
    solution matches the direct evaluator's to the Ewald tolerance."""
    import dataclasses

    import jax.numpy as jnp

    from skellysim_tpu.params import Params
    from skellysim_tpu.system import System

    dtype = jnp.float64
    base = Params(eta=1.0, dt_initial=1e-2, t_final=1.0, gmres_tol=1e-9,
                  adaptive_timestep_flag=False, ewald_tol=1e-8,
                  ewald_min_sources=0)
    sols = {}
    for ev in ("direct", "ewald"):
        fibers, shell, shape, bodies = _coupled_ewald_scene(dtype)
        params = dataclasses.replace(base, pair_evaluator=ev)
        system = System(params, shell_shape=shape)
        state = system.make_state(fibers=fibers, shell=shell, bodies=bodies)
        _, solution, info = system.step(state)
        assert bool(info.converged), ev
        sols[ev] = np.asarray(solution)
    err = (np.linalg.norm(sols["ewald"] - sols["direct"])
           / np.linalg.norm(sols["direct"]))
    assert err < 1e-5, err


@pytest.mark.slow
def test_mixed_precision_with_ewald_reaches_gmres_tol():
    """mixed + ewald: the f64 refinement residual and prep flows stay DENSE
    (role-gated plan withholding), so a deliberately coarse ewald_tol=1e-4
    Krylov interior still refines to the 1e-10 explicit residual. Guards the
    regression where the refinement matvec leaked through the Ewald
    evaluator and plateaued at ewald_tol."""
    import dataclasses

    import jax.numpy as jnp

    from skellysim_tpu.params import Params
    from skellysim_tpu.system import System

    dtype = jnp.float64
    fibers, shell, shape, bodies = _coupled_ewald_scene(dtype)
    params = Params(eta=1.0, dt_initial=1e-2, t_final=1.0, gmres_tol=1e-10,
                    solver_precision="mixed", refine_pair_impl="exact",
                    pair_evaluator="ewald", ewald_tol=1e-4,
                    ewald_min_sources=0, adaptive_timestep_flag=False)
    system = System(params, shell_shape=shape)
    state = system.make_state(fibers=fibers, shell=shell, bodies=bodies)
    _, _, info = system.step(state)
    assert bool(info.converged)
    assert float(info.residual_true) <= 1e-10, float(info.residual_true)
