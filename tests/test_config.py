"""Config schema layer: TOML round-trip, validation, generators, param_tools.

Mirrors the role of the reference's config toolkit
(`/root/reference/src/skelly_sim/skelly_config.py`, `param_tools.py`).
"""

import numpy as np
import pytest

from skellysim_tpu.config import (Body, Config, ConfigEllipsoidal,
                                  ConfigRevolution, ConfigSpherical, Fiber,
                                  Point, load_config, param_tools,
                                  perturbed_fiber_positions, to_runtime_params,
                                  toml_io, unpack)


def test_toml_round_trip_scalars_and_tables(tmp_path):
    data = {
        "params": {"eta": 1.5, "seed": 42, "adaptive_timestep_flag": True,
                   "name": 'quote"inside', "nested": {"x": [1.0, 2.0, 3.0]}},
        "fibers": [{"n_nodes": 32, "length": 1.0}, {"n_nodes": 16, "length": 2.0}],
    }
    p = tmp_path / "t.toml"
    toml_io.dump(data, str(p))
    back = toml_io.load(str(p))
    assert back == data


def test_config_save_load_round_trip(tmp_path):
    cfg = ConfigSpherical()
    cfg.params.eta = 0.9
    cfg.params.dynamic_instability.nucleation_rate = 30.0
    cfg.periphery.radius = 4.25
    cfg.periphery.n_nodes = 1000
    fib = Fiber(n_nodes=24, length=0.8, bending_rigidity=1e-2)
    fib.fill_node_positions(np.zeros(3), np.array([0.0, 0.0, 1.0]))
    cfg.fibers = [fib]
    cfg.bodies = [Body(radius=0.5, n_nodes=400, external_force=[0.0, 0.0, -1.0])]
    cfg.point_sources = [Point(position=[0.0, 0.0, 1.0], force=[1.0, 0.0, 0.0])]
    path = tmp_path / "skelly_config.toml"
    cfg.save(str(path))

    back = load_config(str(path))
    assert isinstance(back, ConfigSpherical)
    assert back.params.eta == 0.9
    assert back.params.dynamic_instability.nucleation_rate == 30.0
    assert back.periphery.radius == 4.25
    assert len(back.fibers) == 1 and back.fibers[0].n_nodes == 24
    np.testing.assert_allclose(back.fibers[0].x, fib.x)
    assert back.bodies[0].external_force == [0.0, 0.0, -1.0]
    assert back.point_sources[0].force == [1.0, 0.0, 0.0]


def test_validation_rejects_numpy_and_unknown(tmp_path):
    cfg = Config()
    cfg.fibers = [Fiber()]
    cfg.fibers[0].length = np.float64(1.0)  # numpy scalar → rejected
    with pytest.raises(ValueError, match="numpy"):
        cfg.save(str(tmp_path / "bad.toml"))

    cfg2 = Config()
    cfg2.typo_field = 3  # unknown attribute → rejected
    with pytest.raises(ValueError, match="unknown attribute"):
        cfg2.save(str(tmp_path / "bad2.toml"))


def test_envelope_numpy_scalars_round_trip(tmp_path):
    """Regression: numpy scalars inside dict fields (periphery.envelope) used
    to bypass unpack() and emit invalid TOML like `T = np.float64(0.72)`."""
    cfg = ConfigRevolution()
    cfg.periphery.envelope = {
        "n_nodes_target": np.int64(400), "lower_bound": np.float64(-3.75),
        "upper_bound": 3.75, "height": "0.72 * (1 - (x/3.75)**2) * 3.75",
    }
    path = tmp_path / "rev.toml"
    cfg.save(str(path))
    back = load_config(str(path))
    assert back.periphery.envelope["n_nodes_target"] == 400
    assert back.periphery.envelope["lower_bound"] == -3.75

    cfg.periphery.envelope = {"bad": object()}
    with pytest.raises(ValueError, match="unsupported type"):
        cfg.save(str(path))


def test_fill_node_positions_straight_line():
    fib = Fiber(n_nodes=8, length=2.0)
    fib.fill_node_positions(np.array([1.0, 0, 0]), np.array([0, 0, 1.0]))
    x = np.asarray(fib.x).reshape(8, 3)
    np.testing.assert_allclose(x[0], [1, 0, 0], atol=1e-14)
    np.testing.assert_allclose(x[-1], [1, 0, 2.0], atol=1e-14)
    seg = np.linalg.norm(np.diff(x, axis=0), axis=1)
    np.testing.assert_allclose(seg, 2.0 / 7, atol=1e-14)


def test_perturbed_fiber_arclength_and_endpoints():
    rng = np.random.default_rng(0)
    L = 1.0
    x = perturbed_fiber_positions(0.05, L, np.array([1.0, 1.0, 1.0]),
                                  np.array([0.0, 0.0, 1.0]), 64, rng=rng)
    assert x.shape == (64, 3)
    np.testing.assert_allclose(x[0], [1, 1, 1], atol=1e-9)
    # arc length ≈ L, and node spacing uniform in arc length
    seg = np.linalg.norm(np.diff(x, axis=0), axis=1)
    assert abs(seg.sum() - L) < 1e-3
    assert seg.std() / seg.mean() < 1e-2
    # perturbation vanishes at both ends: end-to-end vector along normal
    ee = x[-1] - x[0]
    assert abs(ee[0]) < 1e-6 and abs(ee[1]) < 1e-6


def test_spherical_fiber_placement_min_separation():
    cfg = ConfigSpherical()
    cfg.periphery.radius = 5.0
    cfg.fibers = [Fiber(n_nodes=8, length=1.0) for _ in range(40)]
    cfg.periphery.move_fibers_to_surface(cfg.fibers, ds_min=0.5, verbose=False,
                                         rng=np.random.default_rng(3))
    ends = np.array([f.x[0:3] for f in cfg.fibers])
    r = np.linalg.norm(ends, axis=1)
    np.testing.assert_allclose(r, 5.0, rtol=1e-6)
    d = np.linalg.norm(ends[:, None] - ends[None, :], axis=-1)
    np.fill_diagonal(d, np.inf)
    assert d.min() >= 0.5
    # fibers point inward: tip radius < base radius
    tips = np.array([f.x[-3:] for f in cfg.fibers])
    assert np.all(np.linalg.norm(tips, axis=1) < r)


def test_ellipsoidal_fiber_placement():
    cfg = ConfigEllipsoidal()
    cfg.periphery.a, cfg.periphery.b, cfg.periphery.c = 6.0, 4.0, 4.0
    cfg.fibers = [Fiber(n_nodes=8, length=0.5) for _ in range(20)]
    cfg.periphery.move_fibers_to_surface(cfg.fibers, ds_min=0.3, verbose=False,
                                         rng=np.random.default_rng(5))
    ends = np.array([f.x[0:3] for f in cfg.fibers])
    lvl = (ends[:, 0] / (6.0 / 1.04)) ** 2 + (ends[:, 1] / (4.0 / 1.04)) ** 2 \
        + (ends[:, 2] / (4.0 / 1.04)) ** 2
    np.testing.assert_allclose(lvl, 1.0, atol=0.05)


def test_revolution_fiber_placement():
    cfg = ConfigRevolution()
    cfg.periphery.envelope = {
        "n_nodes_target": 400,
        "lower_bound": -3.75, "upper_bound": 3.75,
        "height": "0.5 * T * ((1 + 2*x/length)**p1) * ((1 - 2*x/length)**p2) * length",
        "T": 0.72, "p1": 0.4, "p2": 0.2, "length": 7.5,
    }
    cfg.fibers = [Fiber(n_nodes=8, length=0.3) for _ in range(10)]
    cfg.periphery.move_fibers_to_surface(cfg.fibers, ds_min=0.2, verbose=False,
                                         rng=np.random.default_rng(7))
    ends = np.array([f.x[0:3] for f in cfg.fibers])
    # minus ends lie on the surface: y² + z² = h(x)²
    from skellysim_tpu.periphery.shapes import Envelope
    env = Envelope(cfg.periphery.envelope)
    h = env.raw_height(ends[:, 0])
    np.testing.assert_allclose(np.hypot(ends[:, 1], ends[:, 2]), h, rtol=1e-6)


def test_body_nucleation_sites_and_placement():
    body = Body(radius=1.0, position=[1.0, 2.0, 3.0], n_nucleation_sites=20)
    body.generate_nucleation_sites(0.3, verbose=False,
                                   rng=np.random.default_rng(11))
    sites = np.asarray(body.nucleation_sites).reshape(20, 3)
    r = np.linalg.norm(sites - np.array([1.0, 2.0, 3.0]), axis=1)
    np.testing.assert_allclose(r, 1.0, rtol=1e-9)
    d = np.linalg.norm(sites[:, None] - sites[None, :], axis=-1)
    np.fill_diagonal(d, np.inf)
    assert d.min() >= 0.3


def test_to_runtime_params():
    cfg = Config()
    cfg.params.gmres_tol = 1e-9
    cfg.params.dynamic_instability.v_growth = 0.75
    rp = to_runtime_params(cfg.params)
    assert rp.gmres_tol == 1e-9
    assert rp.dynamic_instability.v_growth == 0.75


def test_param_tools_uniform_on_sphere():
    rng = np.random.default_rng(0)

    def sphere(t, u):
        return np.stack([np.cos(t) * np.sin(u), np.sin(t) * np.sin(u),
                         np.cos(u) * np.ones_like(t)])

    area = param_tools.surface_area(sphere, 0, 2 * np.pi, 0, np.pi,
                                    t_precision=200, u_precision=200)
    assert abs(area - 4 * np.pi) / (4 * np.pi) < 1e-3

    pts = param_tools.r_surface(4000, sphere, 0, 2 * np.pi, 0, np.pi, rng=rng)[0].T
    np.testing.assert_allclose(np.linalg.norm(pts, axis=1), 1.0, atol=1e-3)
    # uniform by area → each octant gets ~1/8
    octant = (pts[:, 0] > 0) & (pts[:, 1] > 0) & (pts[:, 2] > 0)
    assert abs(octant.mean() - 0.125) < 0.02
    # z uniform on [-1, 1] for a uniform sphere sample
    assert abs(pts[:, 2].mean()) < 0.05


def test_param_tools_arc():
    def helix(t):
        return np.stack([np.cos(t), np.sin(t), 0.5 * t])

    L = param_tools.arc_length(helix, 0, 4 * np.pi, precision=4000)
    assert abs(L - 4 * np.pi * np.sqrt(1.25)) / L < 1e-4
    pts, ts, ss = param_tools.r_arc(500, helix, 0, 4 * np.pi,
                                    rng=np.random.default_rng(1))
    assert pts.shape == (3, 500)
    # uniform in arc length → t uniform (constant speed curve)
    assert abs(ts.mean() - 2 * np.pi) / (2 * np.pi) < 0.1


def test_param_tools_from_data_variants():
    """Data-driven sampling parity (`param_tools.py:10-123,237-394`)."""
    # curve data: a unit-speed helix sampled densely
    t = np.linspace(0, 4 * np.pi, 2000)
    helix = np.stack([np.cos(t), np.sin(t), 0.5 * t])
    t2, cum = param_tools.arc_cumulator(t, helix)
    L_exact = 4 * np.pi * np.sqrt(1.25)
    assert abs(cum[-1] - L_exact) / L_exact < 1e-4

    coords, ts, ss = param_tools.r_arc_from_data(
        800, t, helix, rng=np.random.default_rng(2))
    assert coords.shape == (3, 800)
    # on the curve: radius 1 in xy
    np.testing.assert_allclose(np.hypot(coords[0], coords[1]), 1.0, atol=1e-3)
    # constant-speed curve: s/t constant
    np.testing.assert_allclose(ss / ts, np.sqrt(1.25), rtol=1e-3)

    # surface data: unit sphere grid
    tg = np.linspace(0, 2 * np.pi, 160)
    ug = np.linspace(0, np.pi, 80)
    T, U = np.meshgrid(tg, ug)
    sphere = np.stack([np.cos(T) * np.sin(U), np.sin(T) * np.sin(U), np.cos(U)])
    _, _, cum_S_t, cum_S_u = param_tools.surface_cumulator(T, U, sphere)
    assert abs(cum_S_t[-1] - 4 * np.pi) / (4 * np.pi) < 5e-3
    assert abs(cum_S_u[-1] - 4 * np.pi) / (4 * np.pi) < 5e-3

    pts, rt, ru, _, _ = param_tools.r_surface_from_data(
        3000, T, U, sphere, rng=np.random.default_rng(3))
    assert pts.shape == (3, 3000)
    np.testing.assert_allclose(np.linalg.norm(pts, axis=0), 1.0, atol=2e-3)
    # marginal-CDF sampling on a sphere is uniform in the azimuthal angle
    assert abs(rt.mean() - np.pi) / np.pi < 0.05


def test_param_tools_sample_to_arc():
    """Arc-length samples (incl. negative) land at the right parameters
    (`param_tools.py:154-234`)."""
    def line(t):
        # constant speed 2 -> arc length s maps to t = s/2
        t = np.asarray(t, dtype=float)
        return np.stack([2.0 * t, np.zeros_like(t), np.zeros_like(t)])

    sample = np.array([-3.0, -1.0, 0.0, 0.5, 2.0])
    xs, ts = param_tools.sample_to_arc(sample, line)
    np.testing.assert_allclose(ts, sample / 2.0, atol=1e-4)
    np.testing.assert_allclose(xs[0], sample, atol=1e-4)

    def helix(t):
        t = np.asarray(t, dtype=float)
        return np.stack([np.cos(t), np.sin(t), 0.5 * t])

    # speed sqrt(1.25): s = sqrt(1.25) t
    xs, ts = param_tools.sample_to_arc(np.array([1.0, 5.0]), helix)
    np.testing.assert_allclose(ts, np.array([1.0, 5.0]) / np.sqrt(1.25),
                               rtol=1e-3)
    # t0 offset: arc length measured from t0
    xs, ts = param_tools.sample_to_arc(np.array([0.0]), line, t0=1.5)
    np.testing.assert_allclose(ts, [1.5], atol=1e-4)


def test_param_tools_sample_to_arc_closed_curve():
    """Closed curves (chord bounded by the diameter) still invert arc length
    — chord-based bracketing would fail here."""
    def circle(t):
        t = np.asarray(t, dtype=float)
        return np.stack([np.cos(t), np.sin(t), np.zeros_like(t)])

    # arc length 4.0 > diameter 2: parameter equals arc length on a unit circle
    xs, ts = param_tools.sample_to_arc(np.array([1.0, 4.0]), circle,
                                       precision=4000)
    np.testing.assert_allclose(ts, [1.0, 4.0], rtol=1e-4)


def test_fmm_evaluator_name_maps_to_ewald(tmp_path):
    """The reference's "FMM" evaluator name selects the spectral-Ewald fast
    path; TPU-specific extension fields round-trip through TOML."""
    from skellysim_tpu.config import schema

    cfg = schema.Config()
    cfg.params.pair_evaluator = "FMM"
    cfg.params.solver_precision = "mixed"
    cfg.params.ewald_tol = 1e-7
    path = tmp_path / "skelly_config.toml"
    cfg.save(str(path))
    p = schema.load_config(str(path)).params
    assert p.solver_precision == "mixed"
    assert p.ewald_tol == 1e-7
    rt = schema.to_runtime_params(p)
    assert rt.pair_evaluator == "ewald"
    assert rt.solver_precision == "mixed"
    assert rt.ewald_tol == 1e-7
    rt2 = schema.to_runtime_params(schema.Params(pair_evaluator="ewald"))
    assert rt2.pair_evaluator == "ewald"
    rt3 = schema.to_runtime_params(schema.Params(pair_evaluator="CPU"))
    assert rt3.pair_evaluator == "direct"
    # "spectral" graduated from unknown to the fifth evaluator (PR 17);
    # "PVFMM" — the reference's periodic engine — aliases onto it
    rt4 = schema.to_runtime_params(schema.Params(pair_evaluator="spectral"))
    assert rt4.pair_evaluator == "spectral"
    rt5 = schema.to_runtime_params(schema.Params(pair_evaluator="PVFMM"))
    assert rt5.pair_evaluator == "spectral"
    with pytest.raises(ValueError, match="unknown pair_evaluator"):
        schema.to_runtime_params(schema.Params(pair_evaluator="octopus"))


def test_deformable_body_rejected_at_schema_validation(tmp_path):
    """skelly-scenario satellite: a deformable-body config fails at
    schema-validation time with a structured error naming the reference
    parity stub, instead of failing deep in `builder.build_bodies` ->
    `make_group` at build time."""
    cfg = Config()
    fib = Fiber(n_nodes=8, length=1.0)
    fib.fill_node_positions(np.zeros(3), np.array([0.0, 0.0, 1.0]))
    cfg.fibers = [fib]
    cfg.bodies = [Body(shape="deformable")]
    problems = cfg.validate()
    assert any("deformable" in p and "bodies/deformable.py" in p
               for p in problems), problems
    # save() refuses like every other validation failure
    with pytest.raises(ValueError, match="deformable"):
        cfg.save(str(tmp_path / "bad.toml"))
    # sphere/ellipsoid stay valid
    cfg.bodies = [Body(shape="sphere", radius=0.5)]
    assert not cfg.validate()
