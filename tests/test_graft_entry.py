"""Cold-start regression for the driver entry points.

Round 1 shipped a red MULTICHIP artifact (rc=124): the driver imports
``__graft_entry__`` and calls ``dryrun_multichip(n)`` directly, so the
environment setup that lived in the ``__main__`` guard never ran, and the
session's axon TPU plugin blocked JAX backend init on its tunnel.  These tests
invoke the entry points in a subprocess with a *clean* environment (no
JAX_PLATFORMS / XLA_FLAGS, sitecustomize hooks active) to prove the
self-bootstrap works the way the driver will exercise it.
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _cold_env():
    env = dict(os.environ)
    # Simulate the driver's cold environment: no JAX platform pinning from
    # conftest; the axon sitecustomize hook stays active (that is the point).
    env.pop("JAX_PLATFORMS", None)
    env.pop("XLA_FLAGS", None)
    return env


@pytest.mark.slow
def test_dryrun_multichip_cold_import():
    """Import-and-call, exactly like the driver does — must self-bootstrap."""
    code = "import __graft_entry__ as g; g.dryrun_multichip(8)"
    proc = subprocess.run(
        [sys.executable, "-c", code], cwd=REPO, env=_cold_env(),
        capture_output=True, text=True, timeout=420)
    assert proc.returncode == 0, f"stdout={proc.stdout}\nstderr={proc.stderr}"
    assert "dryrun_multichip(8): ok" in proc.stdout


@pytest.mark.slow
def test_entry_compiles_cold():
    """entry() must produce a jittable fn + args without env setup."""
    code = (
        # entry() itself stays platform-agnostic (the driver compile-checks it
        # on the real TPU); pin CPU here the way conftest does, because the
        # axon plugin blocks on its tunnel even under JAX_PLATFORMS=cpu.
        "from skellysim_tpu.utils.bootstrap import force_cpu_devices\n"
        "force_cpu_devices()\n"
        "import jax\n"
        "import __graft_entry__ as g\n"
        "fn, args = g.entry()\n"
        "out = jax.jit(fn)(*args)\n"
        "jax.block_until_ready(out)\n"
        "print('entry: ok')\n")
    proc = subprocess.run(
        [sys.executable, "-c", code], cwd=REPO, env=_cold_env(),
        capture_output=True, text=True, timeout=420)
    assert proc.returncode == 0, f"stdout={proc.stdout}\nstderr={proc.stderr}"
    assert "entry: ok" in proc.stdout
