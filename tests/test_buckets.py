"""skelly-bucket: capacity-bucket shape polymorphism + warm-program pins.

The acceptance pins of ISSUE 12 (ROADMAP item 4), `test_retrace.py`-style:

* two DIFFERENTLY-SHAPED scenes landing in one capacity bucket share one
  trace — zero compile events on the second (run, ensemble, serve paths);
* a masked-node padded scene matches the unpadded scene through
  `System.step`: padded solution entries are EXACT zeros (bitwise), padded
  state rows pass through bitwise-unchanged, and the live physics matches
  to solver roundoff (like the ensemble vmap plan, reduction shapes change
  with padded vector lengths, so live values agree to ~1 ulp — the same
  bound `fibers.container.grow_capacity` padding has always had);
* the wire is padding-blind: a padded state's trajectory frame is
  byte-identical to the unpadded state's;
* serve admission buckets derive from the policy and admit
  mixed-resolution tuple scenes (slow tier).
"""

import numpy as np
import pytest

import jax.numpy as jnp

from skellysim_tpu.config import schema
from skellysim_tpu.fibers import container as fc
from skellysim_tpu.fibers.matrices import VALID_NODE_COUNTS
from skellysim_tpu.params import Params
from skellysim_tpu.system import BackgroundFlow, System
from skellysim_tpu.system import buckets as bucket_mod
from skellysim_tpu.testing import trace_counting_jit


def _scene(n_fib, n_nodes, seed=5, box=2.0):
    rng = np.random.default_rng(seed)
    t = np.linspace(0, 1.0, n_nodes)
    origins = rng.uniform(-box, box, (n_fib, 3))
    dirs = rng.normal(size=(n_fib, 3))
    dirs /= np.linalg.norm(dirs, axis=1, keepdims=True)
    x = origins[:, None, :] + t[None, :, None] * dirs[:, None, :]
    return fc.make_group(x, lengths=1.0, bending_rigidity=0.01,
                         radius=0.0125)


def _system(**over):
    return System(Params(eta=1.0, dt_initial=1e-3, t_final=1e-2,
                         gmres_tol=1e-10, adaptive_timestep_flag=False,
                         **over))


_BG = BackgroundFlow.make(uniform=(1.0, 0.0, 0.0))


# ------------------------------------------------------------------ policy

def test_policy_defaults_are_identity():
    p = bucket_mod.BucketPolicy()
    assert p.fiber_capacity(7) == 7
    assert p.node_capacity(16) == 16
    assert p.shell_capacity(500) is None
    assert not p.node_polymorphism


def test_policy_ladder_rungs_and_extension():
    p = bucket_mod.BucketPolicy(fiber_ladder=(4, 16), node_ladder=(16, 64),
                                shell_ladder=(100, 400))
    assert p.fiber_capacity(3) == 4
    assert p.fiber_capacity(5) == 16
    assert p.fiber_capacity(17) == 32      # doubles past the last rung
    assert p.node_capacity(8) == 16
    assert p.node_capacity(24) == 64
    assert p.shell_capacity(56) == 100
    assert p.node_polymorphism


def test_policy_validation():
    with pytest.raises(ValueError, match="ascending"):
        bucket_mod.BucketPolicy(fiber_ladder=(8, 4))
    with pytest.raises(ValueError, match="valid fiber resolutions"):
        bucket_mod.BucketPolicy(node_ladder=(10,))
    with pytest.raises(ValueError, match="node_ladder must not be empty"):
        bucket_mod.BucketPolicy(node_ladder=())


def test_runtime_config_round_trip(tmp_path):
    p = tmp_path / "cfg.toml"
    p.write_text("[runtime]\nbucket_ladder = [4, 8]\nnode_ladder = [32]\n"
                 "jax_cache = 'off'\n")
    rc = schema.load_runtime_config(str(p))
    assert rc.bucket_ladder == [4, 8]
    assert rc.jax_cache == "off"
    pol = bucket_mod.BucketPolicy.from_runtime(rc)
    assert pol.fiber_ladder == (4, 8)
    assert pol.node_ladder == (32,)

    p.write_text("[runtime]\nbucket_lader = [4]\n")
    with pytest.raises(ValueError, match="unknown \\[runtime\\] keys"):
        schema.load_runtime_config(str(p))
    p.write_text("[runtime]\nbucket_ladder = [-1]\n")
    pol = bucket_mod.BucketPolicy.from_runtime(
        schema.load_runtime_config(str(p)))
    assert pol.fiber_ladder == bucket_mod.GEOMETRIC_FIBER_LADDER
    # defaults when the table is absent
    p.write_text("[params]\neta = 1.0\n")
    rc = schema.load_runtime_config(str(p))
    assert rc.jax_cache == "auto" and rc.bucket_ladder == []


def test_bucketize_default_policy_is_identity():
    g = _scene(3, 16)
    system = _system()
    state = system.make_state(fibers=g, background=_BG)
    out, key = bucket_mod.bucketize(state, bucket_mod.BucketPolicy())
    assert out.fibers is state.fibers          # untouched, not re-padded
    assert key == bucket_mod.BucketKey(fibers=((3, 16),), shell=None)
    assert "3x16" in key.describe()


# ------------------------------------------------- masked-node discipline

def test_grow_node_capacity_invariants():
    g = _scene(2, 16)
    gp = fc.grow_node_capacity(g, 32)
    assert gp.n_nodes == 32
    assert fc.live_node_count(gp) == 16
    nm = fc.node_mask_np(gp)
    assert nm[:16].all() and not nm[16:].any()
    # padded rows replicate node 0 (silent sources, finite kernels)
    np.testing.assert_array_equal(np.asarray(gp.x)[:, 16:],
                                  np.repeat(np.asarray(g.x)[:, :1], 16,
                                            axis=1))
    # live prefix bitwise-unchanged
    np.testing.assert_array_equal(np.asarray(gp.x)[:, :16], np.asarray(g.x))
    # exact-fit attach keeps shapes but swaps in runtime mats
    ga = fc.grow_node_capacity(g, 16)
    assert ga.n_nodes == 16 and ga.rt_mats is not None
    with pytest.raises(ValueError, match="never shrinks"):
        fc.grow_node_capacity(gp, 16)
    # capacity growth composes with node padding (rt mats ride along)
    gpp = fc.grow_capacity(gp, 4)
    assert gpp.n_fibers == 4 and gpp.rt_mats is gp.rt_mats


def test_masked_node_step_parity():
    """Acceptance pin (b): padded-vs-unpadded `System.step`. Exactness
    splits by construction: everything the masking CONTROLS is bitwise
    (padded solution entries are exact zeros, padded state rows pass
    through untouched); the live values agree to solver roundoff — padding
    changes reduction shapes, the same ~ulp bound the ensemble vmap plan
    and fiber-slot padding document."""
    system = _system()
    g = _scene(3, 16, seed=11)
    st = system.make_state(fibers=g, background=_BG)
    new0, sol0, info0 = system.step(st)
    assert bool(info0.converged)

    gp = fc.grow_capacity(fc.grow_node_capacity(g, 32), 6)
    stp = system.make_state(fibers=gp, background=_BG)
    new1, sol1, info1 = system.step(stp)
    assert bool(info1.converged)
    assert int(info1.iters) == int(info0.iters)

    # bitwise: padded node rows and inactive slots pass through unchanged
    x1 = np.asarray(new1.fibers.x)
    np.testing.assert_array_equal(x1[:3, 16:], np.asarray(gp.x)[:3, 16:])
    np.testing.assert_array_equal(x1[3:], np.asarray(gp.x)[3:])
    # bitwise: padded solution entries solve the identity to exact zero
    sol_mask = np.asarray(gp.rt_mats.sol_mask)
    sol1_f = np.asarray(sol1)[:6 * 4 * 32].reshape(6, -1)
    assert np.abs(sol1_f[:, ~sol_mask]).max() == 0.0
    assert np.abs(sol1_f[3:]).max() == 0.0     # inactive slots: zero RHS
    # live physics to solver roundoff
    np.testing.assert_allclose(x1[:3, :16], np.asarray(new0.fibers.x),
                               rtol=0, atol=1e-12)
    np.testing.assert_allclose(np.asarray(new1.fibers.tension)[:3, :16],
                               np.asarray(new0.fibers.tension),
                               rtol=0, atol=1e-10)
    assert float(info1.fiber_error) < 1e-10


def test_padded_frame_bytes_identical_to_unpadded():
    """The wire is padding-blind: same state, padded vs not, identical
    trajectory frame bytes (active fibers only, live node rows only)."""
    from skellysim_tpu.io.trajectory import frame_bytes, frame_to_state

    system = _system()
    g = _scene(3, 16, seed=4)
    st = system.make_state(fibers=g, background=_BG)
    stp = st._replace(fibers=fc.grow_capacity(fc.grow_node_capacity(g, 32),
                                              8))
    assert frame_bytes(stp) == frame_bytes(st)

    # and the frame re-lands on the bucket through frame_to_state +
    # bucketize (the resume path every front door uses)
    import msgpack

    from skellysim_tpu.io import eigen

    frame = eigen.decode_tree(msgpack.unpackb(frame_bytes(stp), raw=False))
    back = frame_to_state(frame, st)
    policy = bucket_mod.BucketPolicy(fiber_ladder=(8,), node_ladder=(32,))
    back, key = bucket_mod.bucketize(back, policy)
    assert key == bucket_mod.state_key(stp)
    np.testing.assert_array_equal(np.asarray(back.fibers.x),
                                  np.asarray(stp.fibers.x))


# ------------------------------------------------------ zero-compile pins

def test_one_bucket_one_trace_across_scene_shapes():
    """Acceptance pin (a): differently-shaped scenes in one bucket share
    ONE trace of the implicit step — the second scene compiles nothing."""
    system = _system()
    step = trace_counting_jit(system._solve_impl, static_argnames=("pair",))
    policy = bucket_mod.BucketPolicy(fiber_ladder=(4,), node_ladder=(16,))
    for n_fib, n_nodes, seed in ((2, 8, 1), (3, 16, 2), (4, 8, 3)):
        st = system.make_state(fibers=_scene(n_fib, n_nodes, seed=seed),
                               background=_BG)
        st, key = bucket_mod.bucketize(st, policy)
        assert key == bucket_mod.BucketKey(fibers=((4, 16),), shell=None,
                                           rt_nodes=True)
        _, _, info = step(st)
        assert bool(info.converged)
    assert step.trace_count == 1, "a bucketized scene retraced"


def test_observed_jit_zero_compile_events_on_bucket_hit():
    """The runtime twin of the trace pin: with a tracer active, the second
    scene in a bucket emits NO compile event (and the first one's event
    carries the persistent-cache stamp field)."""
    import json

    from skellysim_tpu.obs import tracer as obs_tracer

    system = _system()
    policy = bucket_mod.BucketPolicy(fiber_ladder=(4,), node_ladder=(16,))

    events = []

    class Collect(obs_tracer.Tracer):
        def __init__(self):
            pass

        def emit(self, ev, **fields):
            events.append(dict(ev=ev, **fields))

        def close(self):
            pass

    with obs_tracer.use(Collect()):
        for n_fib, n_nodes, seed in ((2, 8, 1), (3, 16, 2)):
            st = system.make_state(fibers=_scene(n_fib, n_nodes, seed=seed),
                                   background=_BG)
            st, _ = bucket_mod.bucketize(st, policy)
            system.step(st)
    compiles = [e for e in events if e["ev"] == "compile"]
    assert len(compiles) == 1, compiles
    assert "persistent_cache" in compiles[0]
    json.dumps(compiles)  # events stay JSONL-serializable


def test_ensemble_admits_heterogeneous_members_one_program():
    """Ensemble path: members of different shapes bucketize onto one key
    and stack into ONE batched program (the sweep-CLI admission path)."""
    from skellysim_tpu.ensemble.runner import EnsembleRunner

    system = _system()
    runner = EnsembleRunner(system)
    policy = bucket_mod.BucketPolicy(fiber_ladder=(4,), node_ladder=(16,))
    states, keys = [], []
    for n_fib, n_nodes, seed in ((2, 8, 1), (3, 16, 2)):
        st = system.make_state(fibers=_scene(n_fib, n_nodes, seed=seed),
                               background=_BG)
        st, key = bucket_mod.bucketize(st, policy)
        states.append(st)
        keys.append(key)
    assert keys[0] == keys[1]
    ens = runner.make_ensemble(states, [1e-2, 1e-2])
    step = trace_counting_jit(runner.step_impl)
    new_ens, info = step(ens)
    assert bool(np.asarray(info.converged).all())
    step(new_ens)
    assert step.trace_count == 1


# -------------------------------------------------------- shell + serve

@pytest.mark.slow
def test_shell_padding_parity_coupled():
    """Shell-axis pin: a shell padded onto a capacity rung solves the same
    coupled system — identical iteration count, live density to roundoff,
    padded density rows exactly zero."""
    from skellysim_tpu.audit import fixtures
    from skellysim_tpu.periphery import periphery as peri

    system = fixtures.make_system(shell=True)
    state = fixtures.coupled_state(system)
    new0, _, info0 = system.step(state)
    assert bool(info0.converged)

    state_p = state._replace(shell=peri.grow_capacity(state.shell, 72))
    new1, _, info1 = system.step(state_p)
    assert bool(info1.converged)
    assert int(info1.iters) == int(info0.iters)
    d0 = np.asarray(new0.shell.density)
    d1 = np.asarray(new1.shell.density)
    assert np.abs(d1[d0.size:]).max() == 0.0
    np.testing.assert_allclose(d1[:d0.size], d0, rtol=0, atol=1e-9)


@pytest.mark.slow
def test_serve_bucketized_admission_mixed_resolution():
    """Acceptance pin (c): a serve bucket derived from the policy admits a
    MIXED-RESOLUTION tuple scene (smaller per-group counts and coarser
    live resolutions padded onto the template), runs it to completion, and
    keeps the zero-compiles-after-warm gate; an oversized scene is
    rejected with the nearest admissible bucket named in the structured
    error."""
    from skellysim_tpu.config import BackgroundSource, Config, Fiber
    from skellysim_tpu.config.toml_io import dumps
    from skellysim_tpu.serve.server import SimulationServer

    def scene_cfg(spec, shift=0.0):
        cfg = Config()
        cfg.params.dt_initial = cfg.params.dt_write = 0.005
        cfg.params.t_final = 0.01
        cfg.params.gmres_tol = 1e-10
        cfg.params.adaptive_timestep_flag = False
        for i, n in enumerate(spec):
            fib = Fiber(n_nodes=n, length=1.0, bending_rigidity=0.01)
            fib.fill_node_positions(np.array([shift + 2.0 * i, 0.0, 0.0]),
                                    np.array([0.0, 0.0, 1.0]))
            cfg.fibers.append(fib)
        cfg.background = BackgroundSource(uniform=[1.0, 0.0, 0.0])
        return cfg

    def save(cfg, path, runtime=""):
        cfg.save(str(path))
        if runtime:
            with open(path, "a") as fh:
                fh.write(runtime)

    import tempfile

    with tempfile.TemporaryDirectory() as td:
        base = f"{td}/serve.toml"
        # mixed-resolution base: one 16-node and one 24-node group; the
        # node ladder coarsens both onto 32, the fiber ladder to 2 each
        save(scene_cfg((16, 24)), base,
             "\n[serve]\nmax_lanes = 2\nbatch_impl = 'unroll'\n"
             "\n[runtime]\nbucket_ladder = [2]\nnode_ladder = [32]\n")
        server = SimulationServer(base, warmup=True)
        assert len(server.buckets) == 1
        key = server.buckets[0].key
        assert key.fibers == ((2, 32), (2, 32))

        # tenant: SMALLER mixed scene — one 8-node fiber + one 16-node
        # fiber; different live shapes, same bucket
        t_cfg = scene_cfg((8, 16), shift=0.5)
        resp = server.handle_request(
            {"type": "submit", "config": dumps(schema.unpack(t_cfg))})
        assert resp["ok"], resp.get("error")
        while server.any_live():
            server.tick()
        st = server.handle_request({"type": "status",
                                    "tenant": resp["tenant"]})
        assert st["status"] == "finished"
        assert server.metrics.stats()["compiles_after_warm"] == 0

        # rejection names the nearest admissible bucket, structured
        big = scene_cfg((16, 24, 16, 24, 16), shift=1.0)
        rej = server.handle_request(
            {"type": "submit", "config": dumps(schema.unpack(big))})
        assert not rej["ok"]
        assert "nearest_bucket" in rej
        assert rej["nearest_bucket"]["fibers"] == [[2, 32], [2, 32]]
        assert "fits no bucket" in rej["error"]


def test_dynamic_instability_growth_lands_on_ladder():
    from skellysim_tpu.system.buckets import next_fiber_capacity

    assert next_fiber_capacity(3) == 4
    assert next_fiber_capacity(5) == 8
    assert next_fiber_capacity(4097) == 8192
    pol = bucket_mod.BucketPolicy(fiber_ladder=(6, 12))
    assert next_fiber_capacity(5, pol) == 6


def test_dynamic_instability_nucleates_into_node_padded_bucket():
    """Nucleation composes with the node axis: the [di.n_nodes] geometry
    fills a node-capacity-padded slot's live prefix, padding rows take the
    replicated-first-node placeholder, and the group's runtime mats (hence
    its compiled program) survive the slot-fill."""
    from skellysim_tpu.bodies import bodies as bd
    from skellysim_tpu.params import DynamicInstability, Params
    from skellysim_tpu.periphery.precompute import precompute_body
    from skellysim_tpu.system.dynamic_instability import (
        apply_dynamic_instability)
    from skellysim_tpu.utils.rng import SimRNG

    di = DynamicInstability(n_nodes=16, v_growth=0.5, f_catastrophe=0.0,
                            nucleation_rate=1000.0, min_length=0.5,
                            bending_rigidity=0.01, radius=0.0125)
    p = Params(eta=1.0, dt_initial=1e-2, t_final=1.0,
               adaptive_timestep_flag=False, dynamic_instability=di)
    pre = precompute_body("sphere", 100, radius=0.5)
    rng_s = np.random.default_rng(7)
    sites = rng_s.standard_normal((12, 3))
    sites = 0.5 * sites / np.linalg.norm(sites, axis=1, keepdims=True)
    bodies = bd.make_group(pre["node_positions_ref"],
                           pre["node_normals_ref"], pre["node_weights"],
                           nucleation_sites_ref=sites[None], radius=0.5)
    system = System(p)
    g = fc.grow_node_capacity(_scene(2, 16, seed=3), 32)
    state = system.make_state(fibers=g, bodies=bodies)
    out = apply_dynamic_instability(state, p, SimRNG(seed=1))
    fib = out.fibers
    assert fib.rt_mats is not None and fib.n_nodes == 32
    act = np.asarray(fib.active)
    assert act.sum() > 2, "nucleation filled no slots"
    x = np.asarray(fib.x)
    new_slots = np.flatnonzero(act)[2:]
    for s in new_slots:
        # live prefix is the nucleated geometry, pads replicate node 0
        np.testing.assert_array_equal(x[s, 16:], np.repeat(x[s, :1], 16,
                                                           axis=0))
        seg = np.linalg.norm(np.diff(x[s, :16], axis=0), axis=1)
        np.testing.assert_allclose(seg.sum(), di.min_length, rtol=1e-12)


def test_bucketize_to_and_admits():
    g16 = _scene(2, 16)
    st = _system().make_state(fibers=g16, background=_BG)
    key = bucket_mod.BucketKey(fibers=((4, 32),), shell=None, rt_nodes=True)
    assert bucket_mod.admits(key, st)
    out = bucket_mod.bucketize_to(st, key)
    assert bucket_mod.state_key(out) == key
    small = bucket_mod.BucketKey(fibers=((1, 16),), shell=None,
                                 rt_nodes=True)
    assert not bucket_mod.admits(small, st)
    with pytest.raises(ValueError, match="fiber slots"):
        bucket_mod.bucketize_to(st, small)
    wrong_groups = bucket_mod.BucketKey(fibers=((4, 32), (4, 32)),
                                        shell=None, rt_nodes=True)
    assert not bucket_mod.admits(wrong_groups, st)
    with pytest.raises(ValueError, match="resolution group"):
        bucket_mod.bucketize_to(st, wrong_groups)
    # a static-resolution (non-rt) bucket only admits exact resolutions
    static_key = bucket_mod.BucketKey(fibers=((4, 16),), shell=None)
    assert bucket_mod.admits(static_key, st)
    smaller_res = bucket_mod.BucketKey(fibers=((4, 32),), shell=None)
    assert not bucket_mod.admits(smaller_res, st)
    with pytest.raises(ValueError, match="static-"):
        bucket_mod.bucketize_to(st, smaller_res)
