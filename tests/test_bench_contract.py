"""The driver-facing bench contract: `python bench.py` prints exactly ONE
line on stdout and it parses as the {metric, value, unit, vs_baseline}
JSON the round driver records (BENCH_r{N}.json). A bench.py edit that
breaks the contract fails the round artifact silently — this smoke test
runs the real entry point (CPU-forced, tiny budget, probe skipped) in a
subprocess and pins the contract.
"""

import importlib.util
import json
import os
import shutil
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH_JSON = os.path.join(REPO, "BENCH.json")


def _load_bench():
    """Import bench.py as a module (it lives at the repo root, outside the
    package). Its import is jax-free by design — the parent-process rule —
    so loading it in-process is safe."""
    spec = importlib.util.spec_from_file_location(
        "bench_under_test", os.path.join(REPO, "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_provenance_stamp_is_uniform(tmp_path, monkeypatch):
    """skelly-roofline satellite: EVERY bench artifact writer goes through
    _stamp_provenance/_archive_round, so any archived round carries the
    same PROVENANCE_KEYS — with `downscaled` an EXPLICIT bool, false on
    real rounds rather than merely absent."""
    bench = _load_bench()
    assert bench.PROVENANCE_KEYS == ("backend", "jax_version",
                                     "device_kind", "downscaled",
                                     "telemetry_version")
    monkeypatch.setattr(bench, "BENCH_ARCHIVE_DIR", str(tmp_path))
    extra = {"backend": "tpu", "jax_version": "9.9", "device_kind": "TPU v5p"}

    bench._archive_round("SPECTRAL", "r42", {"x": 1}, extra)
    with open(tmp_path / "SPECTRAL_r42.json") as fh:
        doc = json.load(fh)
    for key in bench.PROVENANCE_KEYS:
        assert key in doc, key
    assert doc["downscaled"] is False          # explicit, not absent
    assert doc["round"] == "r42"
    assert doc["backend"] == "tpu"
    assert doc["telemetry_version"] == bench.TELEMETRY_VERSION

    # a downscaled section keeps its flag (bool-coerced, not clobbered)
    bench._archive_round("SPECTRAL", "r43", {"downscaled": True}, extra)
    with open(tmp_path / "SPECTRAL_r43.json") as fh:
        assert json.load(fh)["downscaled"] is True

    # campaign round override: BENCH_ROUND_<GROUP> wins over the constant
    monkeypatch.setenv("BENCH_ROUND_SPECTRAL", "r77")
    bench._archive_round("SPECTRAL", "r42", {}, extra)
    assert (tmp_path / "SPECTRAL_r77.json").exists()


def test_next_round_id_appends_never_overwrites(tmp_path, monkeypatch):
    bench = _load_bench()
    monkeypatch.setattr(bench, "BENCH_ARCHIVE_DIR", str(tmp_path))
    assert bench._next_round_id("widget") == "r01"
    (tmp_path / "WIDGET_r03.json").write_text("{}")
    (tmp_path / "WIDGET_r01.json").write_text("{}")
    assert bench._next_round_id("widget") == "r04"
    # the repo root is scanned too (root-artifact groups like treecode)
    assert int(bench._next_round_id("multichip")[1:]) >= 8


@pytest.mark.slow
def test_bench_prints_one_parseable_json_line(tmp_path):
    saved = None
    if os.path.exists(BENCH_JSON):
        saved = tmp_path / "BENCH.json.saved"
        shutil.copy(BENCH_JSON, saved)
    env = dict(os.environ)
    env.update({"BENCH_FORCE_CPU": "1", "BENCH_BUDGET_S": "120",
                "BENCH_PROBE_S": "1",
                # keep this smoke run's partial ladder out of the real
                # MULTICHIP round artifact, and its span stream out of
                # the real .bench_trace.jsonl (the parent DELETES the
                # trace path at startup)
                "BENCH_MULTICHIP_PATH": str(tmp_path / "MULTICHIP.json"),
                "BENCH_TREECODE_PATH": str(tmp_path / "TREECODE.json"),
                # keep the smoke run's partial scenarios/compile/flight
                # rounds out of the real benchmarks/ history the perf
                # gate diffs
                "BENCH_ARCHIVE_DIR": str(tmp_path / "benchmarks"),
                "BENCH_TRACE_PATH": str(tmp_path / "bench_trace.jsonl")})
    env.pop("JAX_PLATFORMS", None)
    # scrub the conftest's 8-virtual-device pin too: a real `python bench.py`
    # run sees the host's devices, not cores split 8 ways (which slows every
    # section and can flake the budget)
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if not f.startswith("--xla_force_host_platform_device_count")]
    if flags:
        env["XLA_FLAGS"] = " ".join(flags)
    else:
        env.pop("XLA_FLAGS", None)
    try:
        p = subprocess.run([sys.executable, os.path.join(REPO, "bench.py")],
                           capture_output=True, text=True, timeout=540,
                           env=env, cwd=REPO)
        assert p.returncode == 0, p.stderr[-2000:]
        lines = [ln for ln in p.stdout.splitlines() if ln.strip()]
        assert len(lines) == 1, f"stdout must be ONE json line, got: {lines!r}"
        doc = json.loads(lines[0])
        for key in ("metric", "value", "unit", "vs_baseline", "backend",
                    "telemetry_version", "extra"):
            assert key in doc, f"missing {key!r}"
        assert doc["metric"] != "bench_failed", doc
        assert isinstance(doc["value"], (int, float))
        # artifacts share the skelly-scope format stamp (one-format pin;
        # test_obs.py asserts the literal tracks obs.tracer's)
        from skellysim_tpu.obs.tracer import TELEMETRY_VERSION

        assert doc["telemetry_version"] == TELEMETRY_VERSION
        # CPU-forced run must be flagged, never silently downscaled
        assert doc["extra"].get("downscaled") is True
        # provenance stamp (skelly-pulse): artifacts self-describe the
        # runtime + hardware via obs.tracer.provenance — the same keys
        # the telemetry header carries
        assert doc["extra"].get("jax_version"), doc["extra"].keys()
        assert doc["extra"].get("device_kind"), doc["extra"].keys()
        # the mirror artifact parses identically
        with open(BENCH_JSON) as fh:
            assert json.load(fh)["metric"] == doc["metric"]
    finally:
        if saved is not None:
            shutil.copy(saved, BENCH_JSON)
        elif os.path.exists(BENCH_JSON):
            os.unlink(BENCH_JSON)
