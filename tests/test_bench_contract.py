"""The driver-facing bench contract: `python bench.py` prints exactly ONE
line on stdout and it parses as the {metric, value, unit, vs_baseline}
JSON the round driver records (BENCH_r{N}.json). A bench.py edit that
breaks the contract fails the round artifact silently — this smoke test
runs the real entry point (CPU-forced, tiny budget, probe skipped) in a
subprocess and pins the contract.
"""

import json
import os
import shutil
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH_JSON = os.path.join(REPO, "BENCH.json")


@pytest.mark.slow
def test_bench_prints_one_parseable_json_line(tmp_path):
    saved = None
    if os.path.exists(BENCH_JSON):
        saved = tmp_path / "BENCH.json.saved"
        shutil.copy(BENCH_JSON, saved)
    env = dict(os.environ)
    env.update({"BENCH_FORCE_CPU": "1", "BENCH_BUDGET_S": "120",
                "BENCH_PROBE_S": "1",
                # keep this smoke run's partial ladder out of the real
                # MULTICHIP round artifact, and its span stream out of
                # the real .bench_trace.jsonl (the parent DELETES the
                # trace path at startup)
                "BENCH_MULTICHIP_PATH": str(tmp_path / "MULTICHIP.json"),
                "BENCH_TREECODE_PATH": str(tmp_path / "TREECODE.json"),
                # keep the smoke run's partial scenarios/compile/flight
                # rounds out of the real benchmarks/ history the perf
                # gate diffs
                "BENCH_ARCHIVE_DIR": str(tmp_path / "benchmarks"),
                "BENCH_TRACE_PATH": str(tmp_path / "bench_trace.jsonl")})
    env.pop("JAX_PLATFORMS", None)
    # scrub the conftest's 8-virtual-device pin too: a real `python bench.py`
    # run sees the host's devices, not cores split 8 ways (which slows every
    # section and can flake the budget)
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if not f.startswith("--xla_force_host_platform_device_count")]
    if flags:
        env["XLA_FLAGS"] = " ".join(flags)
    else:
        env.pop("XLA_FLAGS", None)
    try:
        p = subprocess.run([sys.executable, os.path.join(REPO, "bench.py")],
                           capture_output=True, text=True, timeout=540,
                           env=env, cwd=REPO)
        assert p.returncode == 0, p.stderr[-2000:]
        lines = [ln for ln in p.stdout.splitlines() if ln.strip()]
        assert len(lines) == 1, f"stdout must be ONE json line, got: {lines!r}"
        doc = json.loads(lines[0])
        for key in ("metric", "value", "unit", "vs_baseline", "backend",
                    "telemetry_version", "extra"):
            assert key in doc, f"missing {key!r}"
        assert doc["metric"] != "bench_failed", doc
        assert isinstance(doc["value"], (int, float))
        # artifacts share the skelly-scope format stamp (one-format pin;
        # test_obs.py asserts the literal tracks obs.tracer's)
        from skellysim_tpu.obs.tracer import TELEMETRY_VERSION

        assert doc["telemetry_version"] == TELEMETRY_VERSION
        # CPU-forced run must be flagged, never silently downscaled
        assert doc["extra"].get("downscaled") is True
        # provenance stamp (skelly-pulse): artifacts self-describe the
        # runtime + hardware via obs.tracer.provenance — the same keys
        # the telemetry header carries
        assert doc["extra"].get("jax_version"), doc["extra"].keys()
        assert doc["extra"].get("device_kind"), doc["extra"].keys()
        # the mirror artifact parses identically
        with open(BENCH_JSON) as fh:
            assert json.load(fh)["metric"] == doc["metric"]
    finally:
        if saved is not None:
            shutil.copy(saved, BENCH_JSON)
        elif os.path.exists(BENCH_JSON):
            os.unlink(BENCH_JSON)
