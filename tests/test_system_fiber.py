"""End-to-end single-fiber physics oracles.

TPU-native analogues of the reference integration tests:
* `tests/combined/test_fiber_uniform_background.py` — a free fiber advected by a
  uniform background flow moves at exactly the background velocity
  (rel. error < 1e-13).
* a free fiber with no forcing stays put and keeps tension ~ -penalty-free
  steady solution (sanity).
"""

import numpy as np
import pytest

import jax.numpy as jnp

from skellysim_tpu.fibers import container as fc
from skellysim_tpu.params import Params
from skellysim_tpu.system import BackgroundFlow, System


def straight_fiber(n=8, length=0.75, origin=(0.0, 0.0, 0.0), direction=(0.0, 0.0, 1.0)):
    t = np.linspace(0, 1, n)
    origin = np.asarray(origin)
    direction = np.asarray(direction) / np.linalg.norm(direction)
    x = origin[None, :] + length * t[:, None] * direction[None, :]
    return x[None, :, :]  # [1, n, 3]


def test_fiber_uniform_background_advection():
    """Mirror of the reference config: eta=0.7, dt=1e-4, t_final=1e-2, n=8,
    L=0.75, E=0.0025, uniform background (1, 2, 3)."""
    params = Params(eta=0.7, dt_initial=1e-4, dt_min=1e-5, dt_max=1e-4,
                    t_final=1e-2, gmres_tol=1e-10, adaptive_timestep_flag=False)
    system = System(params)

    fibers = fc.make_group(straight_fiber(), lengths=0.75,
                           bending_rigidity=0.0025, radius=0.0125)
    background = BackgroundFlow.make(uniform=(1.0, 2.0, 3.0))
    state = system.make_state(fibers=fibers, background=background)

    x0 = np.asarray(state.fibers.x[0])
    t0 = float(state.time)
    state = system.run(state)
    xf = np.asarray(state.fibers.x[0])
    tf = float(state.time)

    v_meas = np.linalg.norm((xf[0] - x0[0]) / (tf - t0))
    v_theory = np.linalg.norm([1.0, 2.0, 3.0])
    rel_error = abs(1 - v_meas / v_theory)
    assert rel_error < 1e-13, rel_error

    # the whole fiber translates rigidly
    disp = xf - x0
    np.testing.assert_allclose(disp - disp[0][None, :], 0.0, rtol=0, atol=1e-8)


def test_fiber_no_forcing_stays_put():
    params = Params(eta=1.0, dt_initial=1e-3, t_final=5e-3, gmres_tol=1e-12,
                    adaptive_timestep_flag=False)
    system = System(params)
    fibers = fc.make_group(straight_fiber(n=16, length=1.0),
                           lengths=1.0, bending_rigidity=0.01, radius=0.0125)
    state = system.make_state(fibers=fibers)
    x0 = np.asarray(state.fibers.x)
    state = system.run(state)
    xf = np.asarray(state.fibers.x)
    np.testing.assert_allclose(xf, x0, atol=1e-9)


def test_step_reports_convergence():
    params = Params(eta=0.7, dt_initial=1e-4, t_final=1e-3, gmres_tol=1e-10,
                    adaptive_timestep_flag=False)
    system = System(params)
    fibers = fc.make_group(straight_fiber(), lengths=0.75,
                           bending_rigidity=0.0025, radius=0.0125)
    state = system.make_state(fibers=fibers,
                              background=BackgroundFlow.make(uniform=(1.0, 0, 0)))
    _, _, info = system.step(state)
    assert bool(info.converged)
    assert int(info.iters) > 0
    assert float(info.residual) <= params.gmres_tol
    assert float(info.fiber_error) < 1e-6


@pytest.mark.slow  # the profiler capture adds ~20 s of pure tracing overhead
# to an otherwise-covered run loop (fast-tier budget: the 'not slow' tier
# sits against the 870s timeout)
def test_run_with_profiler_trace(tmp_path):
    """profile_dir captures an XLA profiler trace of the run loop
    (SURVEY.md §5.1 structured-profiling upgrade)."""
    import os

    import numpy as np

    from skellysim_tpu.fibers import container as fc
    from skellysim_tpu.params import Params
    from skellysim_tpu.system import System
    from skellysim_tpu.system.sources import BackgroundFlow

    t = np.linspace(0, 1, 16)
    x = np.stack([np.zeros(16), np.zeros(16), t], axis=-1)
    fibers = fc.make_group(x[None], lengths=1.0, bending_rigidity=0.01,
                           radius=0.0125)
    system = System(Params(dt_initial=0.01, t_final=0.02,
                           adaptive_timestep_flag=False))
    state = system.make_state(fibers=fibers,
                              background=BackgroundFlow.make(uniform=[0, 0, 1.0]))
    prof = str(tmp_path / "prof")
    system.run(state, max_steps=1, profile_dir=prof)
    found = [os.path.join(dp, f) for dp, _, fs in os.walk(prof) for f in fs]
    assert found, "no profiler artifacts written"


def test_adaptive_rejection_aborts_below_dt_min():
    """The adaptive loop's hard abort when dt underflows dt_min
    (`system.cpp:548-551`): an unreachable fiber_error_tol forces every
    step to be rejected and halved until the RuntimeError fires."""
    params = Params(eta=0.7, dt_initial=1e-3, dt_min=4e-4, dt_max=1e-3,
                    beta_down=0.5, t_final=1.0, gmres_tol=1e-10,
                    fiber_error_tol=1e-30,  # nothing can meet this
                    adaptive_timestep_flag=True)
    system = System(params)
    fibers = fc.make_group(straight_fiber(), lengths=0.75,
                           bending_rigidity=0.0025, radius=0.0125)
    background = BackgroundFlow.make(uniform=(1.0, 2.0, 3.0))
    state = system.make_state(fibers=fibers, background=background)
    with pytest.raises(RuntimeError, match="dt_min"):
        system.run(state)
