"""skelly-spectral: the particle-mesh Ewald evaluator vs dense periodic oracles.

The spectral evaluator (`ops.spectral`) is the fifth pair evaluator — the
periodic answer to the reference's PVFMM slot. Every claim is pinned against
an independently-built dense periodic sum (real-space image shells + an
explicit wave-space lattice + the slab's k_perp = 0 column closed forms),
whose own truncation is validated by xi-invariance: the Ewald split parameter
is arbitrary, so two different xi values must produce the same physical sum
to well under the plan tolerance.
"""

import dataclasses
import math

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from skellysim_tpu.ops import ewald, spectral

SQPI = math.sqrt(math.pi)


# ------------------------------------------------------------ dense oracles

def _near_screened(d, f, xi):
    """Screened near-kernel sum over the source axis; d [t,s,3], f [s,3]."""
    r2 = np.sum(d * d, axis=-1)
    mask = r2 > 1e-14
    r = np.sqrt(np.where(mask, r2, 1.0))
    rinv = np.where(mask, 1.0 / r, 0.0)
    erfc = np.where(mask, np.vectorize(math.erfc)(xi * r), 0.0)
    gauss = (2 * xi / SQPI) * np.exp(-(xi * r) ** 2) * mask
    df = np.einsum("tsk,sk->ts", d, f)
    a = erfc * rinv
    b = erfc * rinv ** 3
    return np.einsum("ts,sk->tk", a - gauss, f) \
        + np.einsum("ts,tsk->tk", df * (b + gauss * rinv * rinv), d)


def _near_stresslet(d, S, xi):
    """The repo's screened stresslet tile on a numpy displacement block."""
    return np.asarray(ewald.stresslet_disp_block_ewald(
        jnp.asarray(d), jnp.asarray(S), xi))


def _wave_stokeslet(pts, f, k, k2, xi, eta, V):
    phi = (1 + k2 / (4 * xi * xi)) * np.exp(-k2 / (4 * xi * xi))
    fhat = np.exp(-1j * pts @ k.T).T @ f.astype(complex)      # [K,3]
    kf = np.einsum("ki,ki->k", k, fhat)
    proj = fhat - k * (kf / k2)[:, None]
    phase = np.exp(1j * pts @ k.T)                            # [N,K]
    return (phase @ (proj * (phi / k2)[:, None])).real / (eta * V)


def _wave_stresslet(pts, S, k, k2, xi, eta, V):
    """k-sum of uhat_i = (-i phi/(eta k^4)) [k_i kSk - (k^2/2)
    (((S+S^T)k)_i + trS k_i)] — the same multiplier the grid applies."""
    phi = (1 + k2 / (4 * xi * xi)) * np.exp(-k2 / (4 * xi * xi))
    Sh = np.tensordot(np.exp(-1j * pts @ k.T).T,
                      S.astype(complex), axes=(1, 0))         # [K,3,3]
    kSk = np.einsum("ki,kij,kj->k", k, Sh, k)
    Ssym_k = np.einsum("kij,kj->ki", Sh + np.swapaxes(Sh, 1, 2), k)
    trS = np.einsum("kii->k", Sh)
    uhat = (-1j * phi / (eta * k2 * k2))[:, None] * (
        k * kSk[:, None] - 0.5 * k2[:, None] * (Ssym_k + trS[:, None] * k))
    phase = np.exp(1j * pts @ k.T)
    return (phase @ uhat).real / V


def _k_lattice_tp(box, xi, logtol):
    L = np.asarray(box)
    kmax = 2 * xi * math.sqrt(logtol + 6)
    Kn = [int(math.ceil(kmax * Li / (2 * math.pi))) for Li in L]
    ns = np.stack(np.meshgrid(*[np.arange(-K, K + 1) for K in Kn],
                              indexing="ij"), -1).reshape(-1, 3)
    ns = ns[np.any(ns != 0, axis=1)]
    k = 2 * math.pi * ns / L[None, :]
    k2 = np.sum(k * k, axis=1)
    keep = k2 <= kmax * kmax * 1.5
    return k[keep], k2[keep]


def _k_lattice_dp(Lx, Ly, Dz, xi, logtol):
    """k_perp != 0 modes on a z-periodized box big enough that image
    leakage sits far below the oracle's own truncation."""
    kmax = 2 * xi * math.sqrt(logtol + 6)
    Lzb = 8.0 * (Dz + 6.0 / xi) + 3.0 * max(Lx, Ly)
    Kx = int(math.ceil(kmax * Lx / (2 * math.pi)))
    Ky = int(math.ceil(kmax * Ly / (2 * math.pi)))
    Kz = int(math.ceil(kmax * Lzb / (2 * math.pi)))
    nx, ny, nz = np.meshgrid(np.arange(-Kx, Kx + 1),
                             np.arange(-Ky, Ky + 1),
                             np.arange(-Kz, Kz + 1), indexing="ij")
    sel = (nx != 0) | (ny != 0)
    k = np.stack([2 * math.pi * nx[sel] / Lx, 2 * math.pi * ny[sel] / Ly,
                  2 * math.pi * nz[sel] / Lzb], -1)
    k2 = np.sum(k * k, 1)
    keep = k2 <= kmax * kmax * 1.5
    return k[keep], k2[keep], Lx * Ly * Lzb


def oracle_tp(pts, f, box, eta, xi, tol):
    logtol = math.log(1 / tol)
    u = np.zeros((len(pts), 3))
    for px in range(-2, 3):
        for py in range(-2, 3):
            for pz in range(-2, 3):
                shift = np.array([px, py, pz]) * np.asarray(box)
                d = pts[:, None, :] - (pts[None, :, :] + shift)
                u += _near_screened(d, f, xi)
    u /= 8 * math.pi * eta
    k, k2 = _k_lattice_tp(box, xi, logtol)
    u += _wave_stokeslet(pts, f, k, k2, xi, eta, float(np.prod(box)))
    u -= 4 * xi / (SQPI * 8 * math.pi * eta) * f
    return u


def oracle_tp_stresslet(pts, S, box, eta, xi, tol):
    logtol = math.log(1 / tol)
    u = np.zeros((len(pts), 3))
    for px in range(-2, 3):
        for py in range(-2, 3):
            for pz in range(-2, 3):
                shift = np.array([px, py, pz]) * np.asarray(box)
                d = pts[:, None, :] - (pts[None, :, :] + shift)
                u += _near_stresslet(d, S, xi)
    u /= 8 * math.pi * eta
    k, k2 = _k_lattice_tp(box, xi, logtol)
    u += _wave_stresslet(pts, S, k, k2, xi, eta, float(np.prod(box)))
    # no self term: the screened double layer vanishes at r = 0
    return u


def oracle_dp(pts, f, Lx, Ly, eta, xi, tol):
    logtol = math.log(1 / tol)
    u = np.zeros((len(pts), 3))
    for px in range(-2, 3):
        for py in range(-2, 3):
            shift = np.array([px * Lx, py * Ly, 0.0])
            d = pts[:, None, :] - (pts[None, :, :] + shift)
            u += _near_screened(d, f, xi)
    u /= 8 * math.pi * eta
    Dz = pts[:, 2].max() - pts[:, 2].min()
    k, k2, V = _k_lattice_dp(Lx, Ly, Dz, xi, logtol)
    u += _wave_stokeslet(pts, f, k, k2, xi, eta, V)
    # k_perp = 0 column: exact 1-D kernel on the in-plane channels
    dz = pts[:, None, 2] - pts[None, :, 2]
    K1 = -0.5 * np.abs(dz) * np.vectorize(math.erf)(xi * np.abs(dz)) \
        - np.exp(-(xi * dz) ** 2) / (4 * xi * SQPI)
    u[:, 0] += (K1 @ f[:, 0]) / (eta * Lx * Ly)
    u[:, 1] += (K1 @ f[:, 1]) / (eta * Lx * Ly)
    u -= 4 * xi / (SQPI * 8 * math.pi * eta) * f
    return u


def oracle_dp_stresslet(pts, S, Lx, Ly, eta, xi, tol):
    logtol = math.log(1 / tol)
    u = np.zeros((len(pts), 3))
    for px in range(-2, 3):
        for py in range(-2, 3):
            shift = np.array([px * Lx, py * Ly, 0.0])
            d = pts[:, None, :] - (pts[None, :, :] + shift)
            u += _near_stresslet(d, S, xi)
    u /= 8 * math.pi * eta
    Dz = pts[:, 2].max() - pts[:, 2].min()
    k, k2, V = _k_lattice_dp(Lx, Ly, Dz, xi, logtol)
    u += _wave_stresslet(pts, S, k, k2, xi, eta, V)
    # k_perp = 0 column: K2(z) = -erf(xi z)/2 - (xi z/(2 sqrt(pi))) e^{-..}
    dz = pts[:, None, 2] - pts[None, :, 2]
    K2 = -0.5 * np.vectorize(math.erf)(xi * dz) \
        - (xi * dz / (2 * SQPI)) * np.exp(-(xi * dz) ** 2)
    combo = np.stack([S[:, 0, 2] + S[:, 2, 0], S[:, 1, 2] + S[:, 2, 1],
                      S[:, 0, 0] + S[:, 1, 1] + S[:, 2, 2]], -1)
    u += (K2 @ combo) / (2 * eta * Lx * Ly)
    return u


def _relerr(a, b):
    return np.linalg.norm(a - b) / np.linalg.norm(b)


# ------------------------------------------------------------------ scenes

TP_BOX = (2.0, 3.0, 2.5)
DP_LX, DP_LY, DP_DZ = 2.0, 2.4, 1.2
ETA = 1.3


def _tp_cloud(n=40, seed=0):
    rng = np.random.default_rng(seed)
    pts = rng.uniform(0, 1, (n, 3)) * np.asarray(TP_BOX)
    return pts, rng.standard_normal((n, 3)), rng.standard_normal((n, 3, 3))


def _dp_cloud(n=36, seed=1):
    rng = np.random.default_rng(seed)
    pts = rng.uniform(0, 1, (n, 3)) * np.array([DP_LX, DP_LY, DP_DZ])
    return pts, rng.standard_normal((n, 3)), rng.standard_normal((n, 3, 3))


# two (grid, xi) settings per mode: the tolerance drives both the FFT grid
# rung and the split parameter, so the pair of runs covers two genuinely
# different near/far splits of the same sum
@pytest.mark.parametrize("tol", [1e-4, 1e-6])
def test_tp_stokeslet_vs_dense_oracle(tol):
    pts, f, _ = _tp_cloud()
    plan = spectral.plan_spectral(pts, TP_BOX, ETA, tol=tol)
    r = jnp.asarray(pts)
    u = np.asarray(spectral.stokeslet_spectral(plan, r, r, jnp.asarray(f)))
    u_or = oracle_tp(pts, f, TP_BOX, ETA, plan.xi, tol)
    assert _relerr(u, u_or) < tol


@pytest.mark.parametrize("tol", [1e-4, 1e-6])
def test_dp_stokeslet_vs_dense_oracle(tol):
    pts, f, _ = _dp_cloud()
    plan = spectral.plan_spectral(pts, (DP_LX, DP_LY), ETA, tol=tol)
    r = jnp.asarray(pts)
    u = np.asarray(spectral.stokeslet_spectral(plan, r, r, jnp.asarray(f)))
    u_or = oracle_dp(pts, f, DP_LX, DP_LY, ETA, plan.xi, tol)
    assert _relerr(u, u_or) < tol


def test_tp_stresslet_vs_dense_oracle():
    pts, _, S = _tp_cloud()
    tol = 1e-6
    plan = spectral.plan_spectral(pts, TP_BOX, ETA, tol=tol)
    r = jnp.asarray(pts)
    u = np.asarray(spectral.stresslet_spectral(plan, r, r, jnp.asarray(S)))
    u_or = oracle_tp_stresslet(pts, S, TP_BOX, ETA, plan.xi, tol)
    assert _relerr(u, u_or) < tol


def test_dp_stresslet_vs_dense_oracle():
    pts, _, S = _dp_cloud()
    tol = 1e-6
    plan = spectral.plan_spectral(pts, (DP_LX, DP_LY), ETA, tol=tol)
    r = jnp.asarray(pts)
    u = np.asarray(spectral.stresslet_spectral(plan, r, r, jnp.asarray(S)))
    u_or = oracle_dp_stresslet(pts, S, DP_LX, DP_LY, ETA, plan.xi, tol)
    assert _relerr(u, u_or) < tol


def test_oracle_xi_invariance():
    """The oracles' own truncation control: the Ewald split parameter is
    arbitrary, so the dense sums at two different xi must agree far below
    the tolerance the spectral comparisons run at."""
    tol = 1e-6
    pts, f, S = _tp_cloud()
    plan = spectral.plan_spectral(pts, TP_BOX, ETA, tol=tol)
    u1 = oracle_tp(pts, f, TP_BOX, ETA, plan.xi, tol)
    u2 = oracle_tp(pts, f, TP_BOX, ETA, plan.xi * 1.6, tol)
    assert _relerr(u2, u1) < 1e-8
    s1 = oracle_tp_stresslet(pts, S, TP_BOX, ETA, plan.xi, tol)
    s2 = oracle_tp_stresslet(pts, S, TP_BOX, ETA, plan.xi * 1.6, tol)
    assert _relerr(s2, s1) < 1e-8

    pts, f, _ = _dp_cloud()
    plan = spectral.plan_spectral(pts, (DP_LX, DP_LY), ETA, tol=tol)
    d1 = oracle_dp(pts, f, DP_LX, DP_LY, ETA, plan.xi, tol)
    d2 = oracle_dp(pts, f, DP_LX, DP_LY, ETA, plan.xi * 1.5, tol)
    assert _relerr(d2, d1) < 1e-8


# ------------------------------------------------- plan/trace discipline

def test_plan_rung_stable_under_drift():
    """Positions drifting inside the box (and a slab breathing a little in
    z) land on the SAME stripped plan — the bucket-quantization invariant
    that lets the ensemble runner close the plan into a batched trace."""
    pts, _, _ = _tp_cloud()
    p1 = spectral.plan_spectral(pts, TP_BOX, ETA, tol=1e-6)
    p2 = spectral.plan_spectral(
        np.mod(pts + 0.13, np.asarray(TP_BOX)), TP_BOX, ETA, tol=1e-6)
    assert spectral.strip_anchors(p1) == spectral.strip_anchors(p2)

    pts, _, _ = _dp_cloud()
    p1 = spectral.plan_spectral(pts, (DP_LX, DP_LY), ETA, tol=1e-6)
    drift = pts + np.array([0.21, -0.17, 0.02])
    p2 = spectral.plan_spectral(drift, (DP_LX, DP_LY), ETA, tol=1e-6)
    assert spectral.strip_anchors(p1) == spectral.strip_anchors(p2)


def test_grid_ladder_rungs():
    """Grid dims snap UP onto the rung ladder; a custom [runtime]
    grid_ladder overrides the built-in one."""
    pts, _, _ = _tp_cloud()
    plan = spectral.plan_spectral(pts, TP_BOX, ETA, tol=1e-6)
    assert all(m in spectral.GRID_RUNGS for m in plan.M3)
    custom = (20, 40, 80, 160)
    plan2 = spectral.plan_spectral(pts, TP_BOX, ETA, tol=1e-6,
                                   grid_ladder=custom)
    assert all(m in custom for m in plan2.M3)


def test_anchor_hop_reuses_trace():
    """One compiled program across an anchor hop with drifted positions —
    the plan is static, the anchors are traced operands."""
    from skellysim_tpu.testing import trace_counting_jit

    pts, f, _ = _tp_cloud()
    plan = spectral.plan_spectral(pts, TP_BOX, ETA, tol=1e-4)
    r = jnp.asarray(pts)
    fj = jnp.asarray(f)
    step = trace_counting_jit(spectral._stokeslet_spectral_impl.__wrapped__,
                              static_argnames=("plan", "n_self"))
    sp = spectral.strip_anchors(plan)
    anchors = spectral.plan_anchors(plan)
    step(sp, anchors, r, r, fj, len(pts))
    step(sp, anchors + plan.cell_size3[0], r + 0.01, r + 0.01, fj, len(pts))
    assert step.trace_count == 1


# --------------------------------------------------------- System coupling

def _fiber_scene(params, seed=3, n_fib=6, n_nodes=8, length=0.5,
                 lo=0.5, hi=3.0):
    from skellysim_tpu.fibers import container as fc
    from skellysim_tpu.system import BackgroundFlow, System

    rng = np.random.default_rng(seed)
    origins = rng.uniform(lo, hi, (n_fib, 3))
    dirs = rng.normal(size=(n_fib, 3))
    dirs /= np.linalg.norm(dirs, axis=1, keepdims=True)
    t = np.linspace(0, length, n_nodes)
    x = origins[:, None, :] + t[None, :, None] * dirs[:, None, :]
    system = System(params)
    fibers = fc.make_group(x, lengths=length, bending_rigidity=0.01,
                           radius=0.0125)
    state = system.make_state(
        fibers=fibers,
        background=BackgroundFlow.make(uniform=(1.0, 0.0, 0.0)))
    return system, state


def _spectral_params(**over):
    from skellysim_tpu.params import Params

    base = dict(eta=1.0, dt_initial=1e-3, t_final=1e-2, gmres_tol=1e-8,
                adaptive_timestep_flag=False, pair_evaluator="spectral",
                periodic_box=(4.0, 4.0, 4.0), spectral_tol=1e-5)
    base.update(over)
    return Params(**base)


def test_system_requires_matching_periodic_box():
    from skellysim_tpu.system import System

    with pytest.raises(ValueError, match="periodic_box"):
        System(_spectral_params(periodic_box=()))
    with pytest.raises(ValueError, match="periodic_box"):
        System(_spectral_params(pair_evaluator="direct"))


@pytest.mark.slow  # coupled implicit solve through the FFT pipeline (~25s)
def test_system_step_residual_parity():
    """The coupled implicit solve under the spectral evaluator converges to
    the same GMRES tolerance as the dense free-space solve — the operator
    differs (periodic vs free space) but the Krylov contract does not."""
    sols = {}
    for ev in ("direct", "spectral"):
        if ev == "direct":
            params = _spectral_params(pair_evaluator="direct",
                                      periodic_box=())
        else:
            params = _spectral_params()
        # a clustered, longer-fibered scene: enough hydrodynamic coupling
        # that the two operators produce measurably different iterates
        system, state = _fiber_scene(params, n_fib=8, n_nodes=16,
                                     length=1.2, lo=1.2, hi=2.8)
        _, solution, info = system.step(state)
        assert bool(info.converged), ev
        assert float(info.residual) < params.gmres_tol, ev
        sols[ev] = np.asarray(solution)
        assert np.all(np.isfinite(sols[ev])), ev
    # same structure, different operator: the periodic solve must not be a
    # silent bitwise fallthrough to the dense path (the flow-level
    # divergence is pinned by test_spectral_flow_differs_from_dense; the
    # solution-level difference is scene-dependent and can sit below any
    # fixed threshold for stiff fiber-local-dominated systems)
    assert sols["spectral"].shape == sols["direct"].shape
    assert not np.array_equal(sols["spectral"], sols["direct"])


def test_spectral_flow_differs_from_dense():
    """The pair spec actually routes the fiber flows through the periodic
    grid: a dense-vs-spectral flow comparison on the same forces must show
    the periodic-image correction, not a silent dense fallthrough."""
    from skellysim_tpu.fibers import container as fc
    from skellysim_tpu.system.system import fiber_buckets

    system, state = _fiber_scene(_spectral_params())
    pair, anchors = system._pair_args(state)
    assert pair is not None and pair.evaluator == "spectral"

    buckets = fiber_buckets(state.fibers)
    caches = [fc.update_cache(g, system.params.eta, state.dt)
              for g in buckets]
    r_all = system._node_positions(state)
    rng = np.random.default_rng(7)
    fws = [jnp.asarray(rng.standard_normal((g.n_fibers, g.n_nodes, 3)))
           for g in buckets]
    v_spec = system._fiber_flow(state, caches, r_all, fws,
                                subtract_self=True, pair=pair,
                                pair_anchors=anchors)
    v_dense = system._fiber_flow(state, caches, r_all, fws,
                                 subtract_self=True)
    rel = float(jnp.linalg.norm(v_spec - v_dense)
                / jnp.linalg.norm(v_dense))
    assert rel > 1e-5   # periodic images present
    assert rel < 1e-1   # ... as a correction, not a different answer


@pytest.mark.slow  # batched ensemble compile over the FFT pipeline (~30s)
def test_ensemble_accepts_spectral():
    """The runner's host-rebuilt-plan rejection must NOT fire for spectral:
    the bucket-quantized plan is built once and closed into the batched
    trace as a static, with anchors as traced operands — and lane swaps
    must not retrace."""
    from skellysim_tpu.ensemble.runner import EnsembleRunner
    from skellysim_tpu.fibers import container as fc
    from skellysim_tpu.system import BackgroundFlow
    from skellysim_tpu.testing import trace_counting_jit

    params = _spectral_params(spectral_tol=1e-4)
    system, state = _fiber_scene(params)
    runner = EnsembleRunner(system)

    rng = np.random.default_rng(9)
    states = []
    for i in range(2):
        x = np.asarray(state.fibers.x) + 0.01 * i
        fibers = fc.make_group(x, lengths=0.5, bending_rigidity=0.01,
                               radius=0.0125)
        states.append(system.make_state(
            fibers=fibers,
            background=BackgroundFlow.make(uniform=(1.0, 0.0, 0.0))))
    ens = runner.make_ensemble(states, [1e-2] * 2)
    assert runner._pair is not None and runner._pair.evaluator == "spectral"

    step = trace_counting_jit(runner.step_impl, static_argnames=("pair",))
    new_ens, info = step(ens, pair=runner._pair,
                         pair_anchors=runner._pair_anchors)
    assert bool(np.all(np.asarray(info.converged)))
    step(new_ens, pair=runner._pair, pair_anchors=runner._pair_anchors)
    assert step.trace_count == 1


def test_ensemble_still_rejects_host_rebuilt_plans():
    from skellysim_tpu.ensemble.runner import EnsembleRunner
    from skellysim_tpu.params import Params
    from skellysim_tpu.system import System

    system = System(Params(eta=1.0, pair_evaluator="ewald"))
    with pytest.raises(ValueError, match="spectral"):
        EnsembleRunner(system)


def test_evaluator_aliases_cover_spectral():
    from skellysim_tpu.ops.evaluator import EVALUATOR_ALIASES

    assert EVALUATOR_ALIASES["spectral"] == "spectral"
    assert EVALUATOR_ALIASES["pvfmm"] == "spectral"


def test_config_validate_periodic_pairing():
    from skellysim_tpu.config import schema

    def cfg(**params):
        return schema.Config(params=schema.Params(**params))

    def periodic_problems(c):
        return [p for p in c.validate() if "periodic" in p]

    assert not periodic_problems(cfg(pair_evaluator="spectral",
                                     periodic_box=[4.0, 4.0, 4.0]))
    assert not periodic_problems(cfg(pair_evaluator="spectral",
                                     periodic_box=[4.0, 4.0]))
    # the reference alias lands on the spectral evaluator and pairs too
    assert not periodic_problems(cfg(pair_evaluator="PVFMM",
                                     periodic_box=[4.0, 4.0, 4.0]))
    assert periodic_problems(cfg(pair_evaluator="spectral"))
    assert periodic_problems(cfg(pair_evaluator="direct",
                                 periodic_box=[4.0, 4.0, 4.0]))
    assert periodic_problems(cfg(pair_evaluator="spectral",
                                 periodic_box=[4.0]))
    assert periodic_problems(cfg(pair_evaluator="spectral",
                                 periodic_box=[4.0, -1.0, 4.0]))
