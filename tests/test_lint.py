"""Rule-engine tests for skelly-lint (`skellysim_tpu.lint`).

Each rule gets three fixtures: one snippet that must flag, one that must
pass, and one suppressed-with-pragma case. Fixture files are written under a
fake `skellysim_tpu/...` tree in tmp_path so the path-scoped checks
(hot-path dirs, parallel/, seam files) see package-realistic locations.
"""

import os
import subprocess
import sys

import pytest

from skellysim_tpu.lint import RULES, lint_paths

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _write(tmp_path, rel, src):
    path = tmp_path / "skellysim_tpu" / rel
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(src)
    return str(path)


def _rules(findings):
    return [f.rule for f in findings]


# ------------------------------------------------------------ dtype rule

def test_dtype_flags_zeros_without_dtype(tmp_path):
    p = _write(tmp_path, "ops/mod.py", (
        "import jax.numpy as jnp\n"
        "def f(n):\n"
        "    return jnp.zeros((n, 3))\n"))
    assert _rules(lint_paths([p])) == ["dtype-discipline"]


def test_dtype_flags_arange_and_float_literal_payload(tmp_path):
    p = _write(tmp_path, "ops/mod.py", (
        "import jax.numpy as jnp\n"
        "def f(n):\n"
        "    idx = jnp.arange(n)\n"
        "    w = jnp.asarray([1.0, 2.0])\n"
        "    return idx, w\n"))
    assert _rules(lint_paths([p])) == ["dtype-discipline"] * 2


def test_dtype_passes_with_explicit_dtype(tmp_path):
    p = _write(tmp_path, "ops/mod.py", (
        "import jax.numpy as jnp\n"
        "def f(n, x):\n"
        "    a = jnp.zeros((n, 3), dtype=x.dtype)\n"
        "    b = jnp.arange(n, dtype=jnp.int32)\n"
        "    c = jnp.asarray([1.0, 2.0], dtype=x.dtype)\n"
        "    d = jnp.zeros_like(x)\n"
        "    return a, b, c, d\n"))
    assert lint_paths([p]) == []


def test_dtype_suppressed_with_pragma(tmp_path):
    p = _write(tmp_path, "ops/mod.py", (
        "import jax.numpy as jnp\n"
        "def f(n):\n"
        "    return jnp.zeros((n, 3))"
        "  # skelly-lint: ignore[dtype-discipline] -- fixture reason\n"))
    assert lint_paths([p]) == []


def test_dtype_recognizes_positional_dtype_slots(tmp_path):
    """arange's dtype is positional arg 3 and eye's is arg 3 — a correctly
    pinned positional dtype must pass, and a positional hardcoded f64 on the
    jit path must flag (review finding: the slot table was off by one)."""
    p = _write(tmp_path, "ops/mod.py", (
        "import jax.numpy as jnp\n"
        "def f(n, x):\n"
        "    a = jnp.arange(0, n, 1, jnp.int32)\n"
        "    b = jnp.eye(n, n, 0, x.dtype)\n"
        "    return a, b\n"))
    assert lint_paths([p]) == []
    q = _write(tmp_path, "ops/mod2.py", (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "@jax.jit\n"
        "def traced(n):\n"
        "    return jnp.arange(0, n, 1, jnp.float64)\n"))
    assert _rules(lint_paths([q])) == ["dtype-discipline"]


def test_dtype_flags_hardcoded_f64_only_on_jit_path(tmp_path):
    src = (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "@jax.jit\n"
        "def traced(x):\n"
        "    return x.astype(jnp.float64)\n"
        "def host_setup(op):\n"
        "    return op.astype(jnp.float64)\n")
    hot = _write(tmp_path, "ops/mod.py", src)
    f = lint_paths([hot])
    assert _rules(f) == ["dtype-discipline"] and f[0].line == 5
    # same code in a declared mixed-precision seam file: exempt
    seam = _write(tmp_path, "ops/df_kernels.py", src)
    assert lint_paths([seam]) == []


# ------------------------------------------------------- trace-hygiene

def test_trace_flags_float_and_np_in_jit_reachable(tmp_path):
    p = _write(tmp_path, "solver/mod.py", (
        "import jax\n"
        "import numpy as np\n"
        "@jax.jit\n"
        "def traced(x):\n"
        "    return helper(x)\n"
        "def helper(x):\n"
        "    return np.sum(x) + float(x[0])\n"))
    # np.sum concretizes (trace-hygiene); float() is a host pull (host-sync)
    assert sorted(_rules(lint_paths([p]))) == ["host-sync", "trace-hygiene"]


def test_trace_passes_host_side_and_lru_cached(tmp_path):
    p = _write(tmp_path, "solver/mod.py", (
        "import functools\n"
        "import jax\n"
        "import numpy as np\n"
        "@functools.lru_cache(maxsize=None)\n"
        "def cached_mats(n):\n"
        "    return np.linspace(0.0, 1.0, n)\n"
        "@jax.jit\n"
        "def traced(x):\n"
        "    return x * cached_mats(x.shape[0])\n"
        "def host_writer(state):\n"
        "    return float(state.time), np.asarray(state.x)\n"))
    assert lint_paths([p]) == []


def test_trace_suppressed_with_function_pragma(tmp_path):
    p = _write(tmp_path, "solver/mod.py", (
        "import jax\n"
        "import numpy as np\n"
        "@jax.jit\n"
        "def traced(x):\n"
        "    return helper(x)\n"
        "def helper(n):  # skelly-lint: ignore-function[trace-hygiene] -- fixture reason\n"
        "    return np.ones(3) + np.zeros(3)\n"))
    assert lint_paths([p]) == []


def test_trace_flags_block_until_ready_anywhere_in_hot_path(tmp_path):
    src = ("def host_loop(x):\n"
           "    return x.block_until_ready()\n")
    hot = _write(tmp_path, "parallel/mod.py", src)
    assert _rules(lint_paths([hot])) == ["trace-hygiene"]
    cold = _write(tmp_path, "io/mod.py", src)
    assert lint_paths([cold]) == []


# ------------------------------------------------------------ host-sync

def test_host_sync_flags_item_and_np_asarray_on_traced(tmp_path):
    p = _write(tmp_path, "system/mod.py", (
        "import jax\n"
        "import numpy as np\n"
        "@jax.jit\n"
        "def traced(x):\n"
        "    y = np.asarray(x)\n"
        "    return y, x.item()\n"))
    assert _rules(lint_paths([p])) == ["host-sync"] * 2


def test_host_sync_allows_literal_payloads_and_host_code(tmp_path):
    p = _write(tmp_path, "system/mod.py", (
        "import jax\n"
        "import numpy as np\n"
        "@jax.jit\n"
        "def traced(x):\n"
        "    mask = np.asarray([1.0, 0.0, 1.0])\n"  # frozen constant: not a sync
        "    n = int(x.shape[0])\n"
        "    return x * mask * n\n"
        "def host_writer(state):\n"  # unreachable: host io may sync freely
        "    return float(state.time), np.asarray(state.x), state.t.item()\n"))
    # the literal np.asarray stays trace-hygiene's business (a frozen
    # constant, not a transfer) — host-sync itself must stay silent
    assert lint_paths([p], rules=["host-sync"]) == []


def test_host_sync_suppressed_with_pragma(tmp_path):
    p = _write(tmp_path, "system/mod.py", (
        "import jax\n"
        "@jax.jit\n"
        "def traced(x):\n"
        "    return x.item()  # skelly-lint: ignore[host-sync] -- fixture reason\n"))
    assert lint_paths([p]) == []


# ----------------------------------------------------------- axis-name

def test_axis_name_flags_literal_in_jit_reachable(tmp_path):
    p = _write(tmp_path, "parallel/mod.py", (
        "import jax\n"
        "from jax import lax\n"
        "@jax.jit\n"
        "def traced(x):\n"
        "    a = lax.psum(x, 'fib')\n"
        "    b = lax.ppermute(x, axis_name='fib', perm=[(0, 1)])\n"
        "    c = lax.all_gather(x, ('fib',), tiled=True)\n"
        "    d = lax.axis_index('fib')\n"
        "    return a + b + c + d\n"))
    assert _rules(lint_paths([p])) == ["axis-name"] * 4


def test_axis_name_passes_symbolic_axis_and_host_code(tmp_path):
    p = _write(tmp_path, "parallel/mod.py", (
        "import jax\n"
        "from jax import lax\n"
        "FIBER_AXIS = 'fib'\n"
        "@jax.jit\n"
        "def traced(x):\n"
        "    return lax.psum(x, FIBER_AXIS) + helper(x, FIBER_AXIS)\n"
        "def helper(x, axis_name):\n"
        "    return lax.pmax(x, axis_name)\n"
        "def host_only(x):\n"
        "    # not jit-reachable: a literal here is test/tooling territory\n"
        "    return lax.psum(x, 'fib')\n"))
    assert lint_paths([p]) == []


def test_axis_name_suppressed_with_pragma(tmp_path):
    p = _write(tmp_path, "parallel/mod.py", (
        "import jax\n"
        "from jax import lax\n"
        "@jax.jit\n"
        "def traced(x):\n"
        "    return lax.psum(x, 'fib')  "
        "# skelly-lint: ignore[axis-name] -- fixture reason\n"))
    assert lint_paths([p]) == []


# -------------------------------------------------- sharding-annotation

def test_sharding_flags_shard_map_without_specs(tmp_path):
    p = _write(tmp_path, "parallel/mod.py", (
        "import jax\n"
        "def f(fn, mesh, x):\n"
        "    return jax.shard_map(fn, mesh=mesh)(x)\n"))
    assert _rules(lint_paths([p])) == ["sharding-annotation"]


def test_sharding_passes_with_specs_and_elsewhere(tmp_path):
    p = _write(tmp_path, "parallel/mod.py", (
        "import jax\n"
        "from jax.sharding import PartitionSpec as P\n"
        "def f(fn, mesh, x, sh):\n"
        "    y = jax.shard_map(fn, mesh=mesh, in_specs=P('i'),\n"
        "                      out_specs=P('i'))(x)\n"
        "    return jax.device_put(y, sh)\n"))
    assert lint_paths([p]) == []


def test_sharding_flags_bare_device_put_in_parallel(tmp_path):
    p = _write(tmp_path, "parallel/mod.py", (
        "import jax\n"
        "def f(x):\n"
        "    return jax.device_put(x)\n"))
    assert _rules(lint_paths([p])) == ["sharding-annotation"]


def test_sharding_suppressed_with_pragma(tmp_path):
    p = _write(tmp_path, "parallel/mod.py", (
        "import jax\n"
        "def f(fn, mesh, x):\n"
        "    # skelly-lint: ignore[sharding-annotation] -- fixture reason\n"
        "    return jax.shard_map(fn, mesh=mesh)(x)\n"))
    assert lint_paths([p]) == []


def test_trace_allows_float_of_literal(tmp_path):
    p = _write(tmp_path, "solver/mod.py", (
        "import jax\n"
        "@jax.jit\n"
        "def traced(x):\n"
        "    lim = float('inf')\n"
        "    n = int(x.shape[0])\n"
        "    return x * lim + n\n"))
    assert lint_paths([p]) == []


def test_unknown_rule_filter_raises():
    with pytest.raises(ValueError, match="unknown rule id"):
        lint_paths(["skellysim_tpu"], rules=["dtype-disciplin"])


def test_function_pragma_above_decorated_def(tmp_path):
    """'Directly above the def' must work when the def is decorated (the
    pragma then sits above the decorator, not the `def` keyword line)."""
    p = _write(tmp_path, "solver/mod.py", (
        "import jax\n"
        "import numpy as np\n"
        "# skelly-lint: ignore-function[trace-hygiene] -- fixture reason\n"
        "@jax.jit\n"
        "def traced(x):\n"
        "    return np.sum(x)\n"))
    assert lint_paths([p]) == []


def test_hardcoded_dtype_on_continuation_line_suppressible(tmp_path):
    """The finding anchors at the call/statement line even when `dtype=`
    sits on a 79-column continuation line, so the statement-line pragma
    works like the missing-dtype sub-checks."""
    p = _write(tmp_path, "ops/mod.py", (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "@jax.jit\n"
        "def traced(n):\n"
        "    return jnp.zeros((n, 3),  # skelly-lint: ignore[dtype-discipline] -- fixture reason\n"
        "                     dtype=jnp.float64)\n"))
    assert lint_paths([p]) == []


# ------------------------------------------------------- pragma hygiene

def test_unused_pragma_is_a_finding(tmp_path):
    p = _write(tmp_path, "ops/mod.py", (
        "import jax.numpy as jnp\n"
        "def f(n):\n"
        "    return jnp.ones(n, dtype=jnp.float32)"
        "  # skelly-lint: ignore[dtype-discipline] -- suppresses nothing\n"))
    f = lint_paths([p])
    assert _rules(f) == ["lint-pragma"]
    assert "unused suppression" in f[0].message


def test_pragma_without_reason_is_a_finding(tmp_path):
    p = _write(tmp_path, "ops/mod.py", (
        "import jax.numpy as jnp\n"
        "def f(n):\n"
        "    return jnp.zeros(n)  # skelly-lint: ignore[dtype-discipline]\n"))
    msgs = [f.message for f in lint_paths([p])]
    assert any("missing its reason" in m for m in msgs)


def test_pragma_with_unknown_rule_is_a_finding(tmp_path):
    p = _write(tmp_path, "ops/mod.py", (
        "x = 1  # skelly-lint: ignore[no-such-rule] -- why\n"))
    msgs = [f.message for f in lint_paths([p])]
    assert any("unknown rule id" in m for m in msgs)


def test_pragma_inside_string_is_inert(tmp_path):
    p = _write(tmp_path, "ops/mod.py", (
        'DOC = "# skelly-lint: ignore[dtype-discipline] -- not a comment"\n'))
    assert lint_paths([p]) == []


def test_removing_a_pragma_reexposes_the_finding(tmp_path):
    src = ("import jax.numpy as jnp\n"
           "def f(n):\n"
           "    return jnp.zeros(n)"
           "  # skelly-lint: ignore[dtype-discipline] -- fixture reason\n")
    p = _write(tmp_path, "ops/mod.py", src)
    assert lint_paths([p]) == []
    (tmp_path / "skellysim_tpu" / "ops" / "mod.py").write_text(
        src.replace("  # skelly-lint: ignore[dtype-discipline] "
                    "-- fixture reason", ""))
    assert _rules(lint_paths([p])) == ["dtype-discipline"]


# ----------------------------------------------------------------- CLI

def test_cli_list_rules_and_exit_codes(tmp_path):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, "-m", "skellysim_tpu.lint", "--list-rules"],
        capture_output=True, text=True, cwd=REPO_ROOT, env=env)
    assert out.returncode == 0
    for rule in RULES:
        assert rule.id in out.stdout

    bad = _write(tmp_path, "ops/mod.py", (
        "import jax.numpy as jnp\n"
        "def f(n):\n"
        "    return jnp.zeros(n)\n"))
    run = subprocess.run(
        [sys.executable, "-m", "skellysim_tpu.lint", bad],
        capture_output=True, text=True, cwd=REPO_ROOT, env=env)
    assert run.returncode == 1
    assert "dtype-discipline" in run.stdout


def test_cli_refuses_paths_that_lint_nothing(tmp_path):
    """A gating invocation that would check zero files must exit 2, not
    report success (review finding: a mistyped-but-existing CI path gated
    nothing while passing)."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    empty = tmp_path / "no_python_here"
    empty.mkdir()
    notpy = tmp_path / "engine.pyc"
    notpy.write_bytes(b"")
    for bad in (str(empty), str(notpy)):
        run = subprocess.run(
            [sys.executable, "-m", "skellysim_tpu.lint", bad],
            capture_output=True, text=True, cwd=REPO_ROOT, env=env)
        assert run.returncode == 2, (bad, run.stdout, run.stderr)


# ----------------------------------------------------------- raw-dma rule

_RAW_DMA_SRC = (
    "import jax\n"
    "from jax.experimental.pallas import tpu as pltpu\n"
    "@jax.jit\n"
    "def entry(x):\n"
    "    return body(x)\n"
    "def body(x):\n"
    "    sem = pltpu.get_barrier_semaphore()\n"
    "    pltpu.semaphore_signal(sem, inc=1, device_id=0)\n"
    "    pltpu.semaphore_wait(sem, 1)\n"
    "    return x\n")


def test_raw_dma_flags_unregistered_module(tmp_path):
    p = _write(tmp_path, "parallel/mod.py", _RAW_DMA_SRC)
    assert _rules(lint_paths([p])) == ["raw-dma"] * 3


def test_raw_dma_exempts_auditable_kernels_modules(tmp_path):
    """Defining the `auditable_kernels()` registration seam IS the
    license: the dma audit check verifies every kernel the module
    registers, so its primitives are not raw."""
    p = _write(tmp_path, "parallel/mod.py", _RAW_DMA_SRC + (
        "def auditable_kernels():\n"
        "    return []\n"))
    assert lint_paths([p]) == []


def test_raw_dma_ignores_unreachable_code(tmp_path):
    # no jit seed anywhere: nothing is jit-reachable, nothing flags
    p = _write(tmp_path, "parallel/mod.py", (
        "from jax.experimental.pallas import tpu as pltpu\n"
        "def host_helper(x):\n"
        "    return pltpu.get_barrier_semaphore()\n"))
    assert lint_paths([p]) == []


def test_raw_dma_suppressed_with_pragma(tmp_path):
    p = _write(tmp_path, "parallel/mod.py", (
        "import jax\n"
        "from jax.experimental.pallas import tpu as pltpu\n"
        "@jax.jit\n"
        "def entry(x):\n"
        "    # skelly-lint: ignore[raw-dma] — migration shim under test\n"
        "    sem = pltpu.get_barrier_semaphore()\n"
        "    return x\n"))
    assert lint_paths([p]) == []


# ----------------------------------------------------------- mul-mask rule

def test_mul_mask_flags_field_times_mask(tmp_path):
    """`x * mask` neutralization: one overflowed lane makes 0 * inf = NaN.
    Both operand orders and the broadcast/cast spellings must flag."""
    p = _write(tmp_path, "ops/mod.py", (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "@jax.jit\n"
        "def f(x, g):\n"
        "    a = x * g.active\n"
        "    b = g.node_mask[:, None] * x\n"
        "    c = x * g.active.astype(x.dtype)\n"
        "    d = (jnp.arange(8, dtype=jnp.int32) <= 3) * x\n"
        "    return a + b + c + d\n"))
    assert _rules(lint_paths([p])) == ["mul-mask"] * 4


def test_mul_mask_passes_where_select_and_occupancy_math(tmp_path):
    """The disciplined twin: jnp.where selection never flags, and
    mask-times-mask occupancy counting is integer math, not field
    neutralization."""
    p = _write(tmp_path, "ops/mod.py", (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "@jax.jit\n"
        "def f(x, w, g):\n"
        "    a = jnp.where(g.active, x, 0.0)\n"
        "    n = g.active * g.node_mask\n"
        "    b = w * x\n"
        "    return a, n, b\n"))
    assert lint_paths([p]) == []


def test_mul_mask_ignores_unreachable_code(tmp_path):
    p = _write(tmp_path, "ops/mod.py", (
        "def host_helper(x, mask):\n"
        "    return x * mask\n"))
    assert lint_paths([p]) == []


def test_mul_mask_suppressed_with_pragma(tmp_path):
    """A pragma with a finiteness argument is the licensed escape hatch;
    an unused one is itself a finding (the pragma stays load-bearing)."""
    p = _write(tmp_path, "ops/mod.py", (
        "import jax\n"
        "@jax.jit\n"
        "def f(x, g):\n"
        "    # skelly-lint: ignore[mul-mask] — x is a bounded quadrature "
        "weight, provably finite\n"
        "    return x * g.active\n"))
    assert lint_paths([p]) == []
    stale = _write(tmp_path, "ops/stale.py", (
        "import jax\n"
        "@jax.jit\n"
        "def f(x, g):\n"
        "    # skelly-lint: ignore[mul-mask] — nothing here needs it\n"
        "    return x + g.active\n"))
    assert _rules(lint_paths([stale])) == ["lint-pragma"]


def test_repo_tree_is_lint_clean():
    """The acceptance gate: the shipped tree has zero unsuppressed findings
    (CI runs the CLI equivalent in every tier)."""
    findings = lint_paths([os.path.join(REPO_ROOT, "skellysim_tpu")])
    assert findings == [], "\n".join(f.render() for f in findings)
