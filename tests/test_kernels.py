"""Kernel-backend consistency tests.

TPU-native analogue of the reference's kernel consistency matrix
(`/root/reference/tests/core/kernel_test.cpp:1-120`): every JAX kernel is compared
against an independent straight-loop NumPy oracle on random sources/targets at the
reference's agreement threshold (err <= 5e-9, `kernel_test.cpp:93`).
"""

import numpy as np
import pytest

from skellysim_tpu.ops import kernels

TOL = 5e-9  # reference agreement gate, applied as both rtol and atol


def _rand(n, seed):
    rng = np.random.default_rng(seed)
    return rng.uniform(-1.0, 1.0, size=(n, 3))


# ---------------------------------------------------------------- NumPy oracles


def np_stokeslet(r_src, r_trg, f, eta):
    u = np.zeros((r_trg.shape[0], 3))
    for t in range(r_trg.shape[0]):
        for s in range(r_src.shape[0]):
            d = r_trg[t] - r_src[s]
            r2 = d @ d
            if r2 == 0.0:
                continue
            r = np.sqrt(r2)
            u[t] += f[s] / r + d * (d @ f[s]) / r**3
    return u / (8 * np.pi * eta)


def np_stresslet(r_dl, r_trg, S, eta):
    u = np.zeros((r_trg.shape[0], 3))
    for t in range(r_trg.shape[0]):
        for s in range(r_dl.shape[0]):
            d = r_trg[t] - r_dl[s]
            r2 = d @ d
            if r2 == 0.0:
                continue
            u[t] += -3.0 * (d @ S[s] @ d) * d / r2**2.5
    return u / (8 * np.pi * eta)


def np_oseen_frgr(r, eta, reg, eps):
    factor = 1.0 / (8 * np.pi * eta)
    if r > eps:
        return factor / r, factor / r**3
    di = 1.0 / np.sqrt(r**2 + reg**2)
    return factor * di, factor * di**3


def np_oseen_contract(r_src, r_trg, rho, eta, reg=5e-3, eps=1e-5):
    u = np.zeros((r_trg.shape[0], 3))
    for t in range(r_trg.shape[0]):
        for s in range(r_src.shape[0]):
            d = r_src[s] - r_trg[t]
            r = np.linalg.norm(d)
            if r == 0.0:
                continue
            fr, gr = np_oseen_frgr(r, eta, reg, eps)
            u[t] += fr * rho[s] + gr * d * (d @ rho[s])
    return u


def np_oseen_tensor(r_src, r_trg, eta, reg=5e-3, eps=1e-5):
    nt, ns = r_trg.shape[0], r_src.shape[0]
    G = np.zeros((3 * nt, 3 * ns))
    for t in range(nt):
        for s in range(ns):
            d = r_trg[t] - r_src[s]
            r = np.linalg.norm(d)
            if r == 0.0:
                continue
            fr, gr = np_oseen_frgr(r, eta, reg, eps)
            G[3 * t:3 * t + 3, 3 * s:3 * s + 3] = fr * np.eye(3) + gr * np.outer(d, d)
    return G


def np_rotlet(r_src, r_trg, rho, eta, reg=5e-3, eps=1e-5):
    u = np.zeros((r_trg.shape[0], 3))
    for t in range(r_trg.shape[0]):
        for s in range(r_src.shape[0]):
            d = r_trg[t] - r_src[s]
            r2 = d @ d
            r = np.sqrt(reg**2 + r2) if r2 < eps**2 else np.sqrt(r2)
            u[t] += np.cross(rho[s], d) / r**3
    return u / (8 * np.pi * eta)


def np_stresslet_times_normal(r, normals, reg=5e-3, eps=1e-5):
    n = r.shape[0]
    M = np.zeros((3 * n, 3 * n))
    for i in range(n):
        for j in range(n):
            if i == j:
                continue
            d = r[i] - r[j]
            rn = np.linalg.norm(d)
            if rn < eps:
                rn = np.sqrt(rn**2 + reg**2)
            M[3 * i:3 * i + 3, 3 * j:3 * j + 3] = (
                -3.0 / (4 * np.pi) * (d @ normals[j]) / rn**5 * np.outer(d, d)
            )
    return M


def np_stresslet_times_normal_times_density(r, normals, rho, reg=5e-3, eps=1e-5):
    n = r.shape[0]
    u = np.zeros((n, 3))
    for i in range(n):
        for j in range(n):
            if i == j:
                continue
            d = r[i] - r[j]
            rn = np.linalg.norm(d)
            if rn < eps:
                rn = np.sqrt(rn**2 + reg**2)
            u[i] += (d @ rho[j]) * (d @ normals[j]) / rn**5 * d
    return u * (-3.0 / (4 * np.pi))


# ----------------------------------------------------------------------- tests


@pytest.mark.parametrize("n_src,n_trg", [(37, 53), (64, 64)])
def test_stokeslet_direct(n_src, n_trg):
    r_src, r_trg, f = _rand(n_src, 0), _rand(n_trg, 1), _rand(n_src, 2)
    got = np.asarray(kernels.stokeslet_direct(r_src, r_trg, f, eta=1.3))
    want = np_stokeslet(r_src, r_trg, f, 1.3)
    np.testing.assert_allclose(got, want, rtol=TOL, atol=TOL)


def test_stokeslet_self_overlap():
    # sources == targets: self term must drop
    r = _rand(20, 3)
    f = _rand(20, 4)
    got = np.asarray(kernels.stokeslet_direct(r, r, f, eta=1.0))
    want = np_stokeslet(r, r, f, 1.0)
    np.testing.assert_allclose(got, want, rtol=TOL, atol=TOL)


def test_stokeslet_blocked_matches_unblocked():
    r_src, r_trg, f = _rand(100, 5), _rand(257, 6), _rand(100, 7)
    a = np.asarray(kernels.stokeslet_direct(r_src, r_trg, f, 1.0, block_size=64))
    b = np.asarray(kernels.stokeslet_direct(r_src, r_trg, f, 1.0, block_size=4096))
    np.testing.assert_allclose(a, b, atol=1e-14)


def test_stresslet_direct():
    rng = np.random.default_rng(8)
    r_dl, r_trg = _rand(31, 9), _rand(45, 10)
    S = rng.uniform(-1, 1, size=(31, 3, 3))
    got = np.asarray(kernels.stresslet_direct(r_dl, r_trg, S, eta=0.9))
    want = np_stresslet(r_dl, r_trg, S, 0.9)
    np.testing.assert_allclose(got, want, rtol=TOL, atol=TOL)


def test_oseen_contract_regularized():
    # include a coincident and a nearly-coincident pair to hit both branches
    r_src = _rand(25, 11)
    r_trg = np.concatenate([_rand(10, 12), r_src[:2], r_src[3:4] + 1e-7])
    rho = _rand(25, 13)
    got = np.asarray(kernels.oseen_contract(r_src, r_trg, rho, eta=1.1))
    want = np_oseen_contract(r_src, r_trg, rho, 1.1)
    np.testing.assert_allclose(got, want, rtol=TOL, atol=TOL)


def test_oseen_tensor():
    r = _rand(16, 14)
    got = np.asarray(kernels.oseen_tensor(r, r, eta=0.7)).reshape(48, 48)
    want = np_oseen_tensor(r, r, 0.7)
    np.testing.assert_allclose(got, want, rtol=TOL, atol=TOL)


def test_rotlet():
    r_src, r_trg, rho = _rand(22, 15), _rand(33, 16), _rand(22, 17)
    got = np.asarray(kernels.rotlet(r_src, r_trg, rho, eta=1.2))
    want = np_rotlet(r_src, r_trg, rho, 1.2)
    np.testing.assert_allclose(got, want, rtol=TOL, atol=TOL)


def test_stresslet_times_normal():
    r, nrm = _rand(18, 18), _rand(18, 19)
    got = np.asarray(kernels.stresslet_times_normal(r, nrm, eta=1.0)).reshape(54, 54)
    want = np_stresslet_times_normal(r, nrm)
    np.testing.assert_allclose(got, want, rtol=TOL, atol=TOL)


def test_stresslet_times_normal_times_density():
    r, nrm, rho = _rand(19, 20), _rand(19, 21), _rand(19, 22)
    got = np.asarray(kernels.stresslet_times_normal_times_density(r, nrm, rho, eta=1.0))
    want = np_stresslet_times_normal_times_density(r, nrm, rho)
    np.testing.assert_allclose(got, want, rtol=TOL, atol=TOL)


def test_double_layer_consistency():
    """stresslet_direct with f_dl = 2 eta n (x) rho == stresslet_times_normal_times_density.

    The identity the periphery/body flows rely on (`src/core/periphery.cpp:68-74`).
    """
    r, nrm, rho = _rand(15, 23), _rand(15, 24), _rand(15, 25)
    eta = 1.7
    f_dl = 2.0 * eta * nrm[:, :, None] * rho[:, None, :]
    a = np.asarray(kernels.stresslet_direct(r, r, f_dl, eta))
    b = np_stresslet_times_normal_times_density(r, nrm, rho)
    np.testing.assert_allclose(a, b, rtol=TOL, atol=TOL)


def test_source_chunked_kernels_match_unchunked():
    import jax.numpy as jnp

    """Forcing a small source_block must not change any kernel value (the
    source-chunked scan path used at BASELINE scale, kernels._pair_sum)."""
    rng = np.random.default_rng(17)
    n_src, n_trg = 300, 101
    r_src = jnp.asarray(rng.uniform(-2, 2, (n_src, 3)))
    r_trg = jnp.asarray(np.concatenate([r_src[:50], rng.uniform(-2, 2, (n_trg - 50, 3))]))
    f = jnp.asarray(rng.standard_normal((n_src, 3)))
    S = jnp.asarray(rng.standard_normal((n_src, 3, 3)))

    for fn, strength in ((kernels.stokeslet_direct, f),
                         (kernels.stresslet_direct, S),
                         (kernels.oseen_contract, f),
                         (kernels.rotlet, f)):
        ref = fn(r_src, r_trg, strength, 1.3)
        chunked = fn(r_src, r_trg, strength, 1.3, source_block=64)
        np.testing.assert_allclose(np.asarray(chunked), np.asarray(ref),
                                   rtol=0, atol=1e-12)


def test_stresslet_times_normal_blocked_matches_dense():
    import jax.numpy as jnp

    rng = np.random.default_rng(23)
    r = jnp.asarray(rng.uniform(-1, 1, (37, 3)))
    nrm = rng.standard_normal((37, 3))
    nrm /= np.linalg.norm(nrm, axis=1, keepdims=True)
    nrm = jnp.asarray(nrm)
    dense = np.asarray(kernels.stresslet_times_normal(r, nrm, 1.0)
                       ).reshape(3 * 37, 3 * 37)
    blocked = kernels.stresslet_times_normal_blocked(r, nrm, 1.0, block_size=8)
    assert blocked.shape == (3 * 37, 3 * 37)
    np.testing.assert_allclose(np.asarray(blocked), dense, rtol=0, atol=1e-13)


def test_stokeslet_mxu_impl_matches_exact():
    """The matmul-form tile agrees with the exact form on well-separated
    clouds (its intended regime) including exact self-pairs."""
    import jax.numpy as jnp

    rng = np.random.default_rng(31)
    r = jnp.asarray(rng.uniform(-10, 10, (500, 3)))
    f = jnp.asarray(rng.standard_normal((500, 3)))
    ref = kernels.stokeslet_direct(r, r, f, 1.0)
    mxu = kernels.stokeslet_direct(r, r, f, 1.0, impl="mxu")
    err = np.linalg.norm(np.asarray(mxu - ref)) / np.linalg.norm(np.asarray(ref))
    assert err < 1e-9, err  # f64 on CPU: subtraction-form noise is ~1e-13
    # and with source chunking
    mxu_c = kernels.stokeslet_direct(r, r, f, 1.0, impl="mxu", source_block=128)
    np.testing.assert_allclose(np.asarray(mxu_c), np.asarray(mxu), atol=1e-12)


def test_stresslet_mxu_impl_matches_exact():
    import jax.numpy as jnp

    rng = np.random.default_rng(37)
    r_src = jnp.asarray(rng.uniform(-10, 10, (400, 3)))
    r_trg = jnp.asarray(np.concatenate([r_src[:100],
                                        rng.uniform(-10, 10, (151, 3))]))
    S = jnp.asarray(rng.standard_normal((400, 3, 3)))
    ref = kernels.stresslet_direct(r_src, r_trg, S, 1.4)
    mxu = kernels.stresslet_direct(r_src, r_trg, S, 1.4, impl="mxu")
    err = np.linalg.norm(np.asarray(mxu - ref)) / np.linalg.norm(np.asarray(ref))
    assert err < 1e-9, err
    mxu_c = kernels.stresslet_direct(r_src, r_trg, S, 1.4, impl="mxu",
                                     source_block=128)
    np.testing.assert_allclose(np.asarray(mxu_c), np.asarray(mxu), atol=1e-12)


@pytest.mark.slow  # heavy coupled-solve integration; sibling fast tests keep the seam covered (ISSUE-9 870s-budget re-triage)
def test_system_solve_with_mxu_kernels_matches_exact():
    """A full coupled solve with kernel_impl='mxu' agrees with the exact
    tiles (well-separated walkthrough geometry — the MXU tiles' regime)."""
    import jax.numpy as jnp

    from skellysim_tpu.fibers import container as fc
    from skellysim_tpu.params import Params
    from skellysim_tpu.system import System
    from skellysim_tpu.testing import make_coupled_parts

    shell, shape, bodies = make_coupled_parts(96, 64, jnp.float64)
    t = np.linspace(0, 1, 16)
    x = (np.array([0.0, 3.0, 0.0])[None, :]
         + t[:, None] * np.array([0.0, 0.0, 1.0]))
    sols = {}
    for impl in ("exact", "mxu"):
        fibers = fc.make_group(x[None], lengths=1.0, bending_rigidity=0.01,
                               radius=0.0125, dtype=jnp.float64)
        system = System(Params(dt_initial=0.1, t_final=1.0, gmres_tol=1e-10,
                               kernel_impl=impl, adaptive_timestep_flag=False),
                        shell_shape=shape)
        state = system.make_state(fibers=fibers, shell=shell, bodies=bodies)
        _, solution, info = system.step(state)
        assert bool(info.converged), impl
        sols[impl] = np.asarray(solution)
    err = (np.linalg.norm(sols["mxu"] - sols["exact"])
           / np.linalg.norm(sols["exact"]))
    assert err < 1e-8, err


def test_morton_sort_preserves_physics_and_orders_locally():
    import jax.numpy as jnp

    from skellysim_tpu.fibers import container as fc

    rng = np.random.default_rng(43)
    nf, n = 64, 8
    origins = rng.uniform(-10, 10, (nf, 3))
    t = np.linspace(0, 1, n)
    x = origins[:, None, :] + t[None, :, None] * np.array([0.0, 0, 1.0])
    g = fc.make_group(x, lengths=rng.uniform(0.5, 2, nf),
                      bending_rigidity=0.01, radius=0.0125,
                      minus_clamped=rng.random(nf) > 0.5)
    gs = fc.sort_fibers_morton(g)
    # a permutation: same multiset of centroids and lengths
    c0 = np.sort(np.asarray(jnp.mean(g.x, axis=1)), axis=0)
    c1 = np.sort(np.asarray(jnp.mean(gs.x, axis=1)), axis=0)
    np.testing.assert_allclose(c0, c1)
    np.testing.assert_allclose(np.sort(np.asarray(g.length)),
                               np.sort(np.asarray(gs.length)))
    # per-fiber state rode along with its positions
    i0 = np.lexsort(np.asarray(g.x[:, 0]).T)
    i1 = np.lexsort(np.asarray(gs.x[:, 0]).T)
    np.testing.assert_allclose(np.asarray(g.length)[i0],
                               np.asarray(gs.length)[i1])
    np.testing.assert_array_equal(np.asarray(g.minus_clamped)[i0],
                                  np.asarray(gs.minus_clamped)[i1])
    # locality: mean distance between consecutive centroids shrinks
    def hop(gr):
        c = np.asarray(jnp.mean(gr.x, axis=1))
        return np.linalg.norm(np.diff(c, axis=0), axis=1).mean()
    assert hop(gs) < hop(g)


def test_mxu_f32_accuracy_envelope():
    """Measured f32 accuracy envelope of the MXU tiles on a Morton-sorted
    fiber cloud: ~2e-3 relative (vs ~4e-6 for the exact tile). That is the
    documented regime — fine as the mixed solver's inner operator (it sets
    the per-sweep contraction, not the final f64 residual), not a
    replacement for the exact tile in accuracy-gated f32 work."""
    import jax.numpy as jnp

    from skellysim_tpu.fibers import container as fc

    rng = np.random.default_rng(9)
    nf, n = 256, 16
    origins = rng.uniform(-10, 10, (nf, 3))
    dirs = rng.normal(size=(nf, 3))
    dirs /= np.linalg.norm(dirs, axis=1, keepdims=True)
    t = np.linspace(0, 1, n)
    x = origins[:, None, :] + t[None, :, None] * dirs[:, None, :]
    g = fc.sort_fibers_morton(fc.make_group(x, lengths=1.0,
                                            bending_rigidity=0.01,
                                            radius=0.0125))
    r64 = jnp.asarray(np.asarray(g.x).reshape(-1, 3))
    f64_ = jnp.asarray(rng.standard_normal((nf * n, 3)))
    ref = np.asarray(kernels.stokeslet_direct(r64, r64, f64_, 1.0))

    r32, f32_ = r64.astype(jnp.float32), f64_.astype(jnp.float32)
    exact = np.asarray(kernels.stokeslet_direct(r32, r32, f32_, 1.0))
    mxu = np.asarray(kernels.stokeslet_direct(r32, r32, f32_, 1.0,
                                              impl="mxu", source_block=512))
    nrm = np.linalg.norm(ref)
    assert np.linalg.norm(exact - ref) / nrm < 5e-5
    assert np.linalg.norm(mxu - ref) / nrm < 1e-2
