"""ParaView reader support: standalone trajectory utility + field writer.

The vtk-dependent reader scripts can't run here; the shared indexer/loader
and the wire-format helpers they consume are tested against trajectories
written by this framework.
"""

import importlib.util
import os

import numpy as np

from skellysim_tpu.io.trajectory import FieldWriter


def _load_utility():
    path = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                        "skellysim_tpu", "paraview_utils", "trajectory_utility.py")
    spec = importlib.util.spec_from_file_location("trajectory_utility", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)   # standalone, like ParaView would exec it
    return mod


def _write_sim(tmp_path):
    from skellysim_tpu.config import BackgroundSource, Config, Fiber
    from skellysim_tpu import cli

    cfg = Config()
    cfg.params.dt_initial = 0.005
    cfg.params.dt_write = 0.005
    cfg.params.t_final = 0.015
    cfg.params.adaptive_timestep_flag = False
    fib = Fiber(n_nodes=16, length=1.0, bending_rigidity=0.01)
    fib.fill_node_positions(np.zeros(3), np.array([0.0, 0.0, 1.0]))
    cfg.fibers = [fib]
    cfg.background = BackgroundSource(uniform=[1.0, 0.0, 0.0])
    path = str(tmp_path / "skelly_config.toml")
    cfg.save(path)
    cli.run(path)
    return str(tmp_path / "skelly_sim.out")


def test_get_frame_info_and_load_frame(tmp_path):
    traj = _write_sim(tmp_path)
    util = _load_utility()
    fhs, fpos, times = util.get_frame_info([traj])
    assert len(times) >= 2 and times == sorted(times)

    frame = util.load_frame(fhs, fpos, len(times) - 1)
    assert frame["time"] == times[-1]
    assert len(frame["fibers"]) == 1
    pts = util.eigen_points(frame["fibers"][0]["x_"])
    assert len(pts) == 16 and len(pts[0]) == 3
    # advected by the uniform background: x-coordinate moved forward
    assert pts[0][0] > 0.0
    for fh in fhs:
        fh.close()


def test_field_writer_roundtrip(tmp_path):
    util = _load_utility()
    path = str(tmp_path / "skelly_sim.vf")
    x = np.arange(12.0).reshape(4, 3)
    v = np.ones((4, 3)) * [1.0, 2.0, 3.0]
    with FieldWriter(path) as fw:
        fw.write_frame(0.0, x, v)
        fw.write_frame(1.0, x + 1, v)

    fhs, fpos, times = util.get_frame_info([path])
    assert times == [0.0, 1.0]
    frames = util.load_field_frame(fhs, fpos, 0)
    assert frames[0]["x_grid"][2] == 4  # cols of the 3 x n encoding
    np.testing.assert_allclose(frames[0]["x_grid"][3:6], x[0])
    np.testing.assert_allclose(frames[0]["v_grid"][3:6], [1.0, 2.0, 3.0])
    for fh in fhs:
        fh.close()


def test_deformable_body_stub_raises(tmp_path):
    from skellysim_tpu import builder
    from skellysim_tpu.bodies.deformable import DeformableBodyNotImplemented
    from skellysim_tpu.config import Body

    import pytest

    with pytest.raises(DeformableBodyNotImplemented):
        builder.build_bodies([Body(shape="deformable")], str(tmp_path),
                             np.float64)
