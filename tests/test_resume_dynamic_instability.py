"""Resume with dynamic instability enabled.

The reference cannot do this (nucleated/clamp state is not restored,
`trajectory_reader.cpp:180-185`, SURVEY.md §5.4 'resume broken with dynamic
instability'); here the full fiber state, binding-site occupancy, and RNG
stream round-trip through the trajectory, so a resumed run continues cleanly.
"""

import numpy as np
import pytest

from skellysim_tpu import builder, cli, precompute
from skellysim_tpu.config import Body, Config
from skellysim_tpu.io.trajectory import TrajectoryReader


def _di_config(tmp_path, t_final):
    cfg = Config()
    cfg.params.eta = 1.0
    cfg.params.dt_initial = 0.05
    cfg.params.dt_write = 0.05
    cfg.params.t_final = t_final
    cfg.params.adaptive_timestep_flag = False
    cfg.params.seed = 3
    cfg.params.dynamic_instability.n_nodes = 16
    cfg.params.dynamic_instability.v_growth = 0.2
    cfg.params.dynamic_instability.f_catastrophe = 0.5
    cfg.params.dynamic_instability.nucleation_rate = 50.0
    cfg.params.dynamic_instability.min_length = 0.4
    cfg.params.dynamic_instability.radius = 0.0125
    cfg.params.dynamic_instability.bending_rigidity = 0.01

    rng = np.random.default_rng(11)
    sites = rng.standard_normal((12, 3))
    sites = 0.5 * sites / np.linalg.norm(sites, axis=1, keepdims=True)
    body = Body(position=[0.0, 0.0, 0.0], shape="sphere", radius=0.5,
                n_nodes=100, nucleation_sites=sites.ravel().tolist())
    cfg.bodies = [body]
    path = str(tmp_path / "skelly_config.toml")
    cfg.save(path)
    return path


@pytest.mark.slow  # 27s e2e run->resume pipeline (fast-tier budget)
def test_resume_with_dynamic_instability(tmp_path):
    cfg_path = _di_config(tmp_path, t_final=0.3)
    precompute.precompute_from_config(cfg_path, verbose=False)
    cli.run(cfg_path)

    traj = str(tmp_path / "skelly_sim.out")
    r1 = TrajectoryReader(traj)
    n_frames_1 = len(r1)
    last_before = r1.load_frame(n_frames_1 - 1)
    fibers_before = last_before["fibers"][1]
    assert len(fibers_before) > 0, "nucleation never fired"
    r1.close()

    # extend t_final and resume
    _di_config(tmp_path, t_final=0.6)
    cli.run(cfg_path, resume=True)

    r2 = TrajectoryReader(traj)
    assert len(r2) > n_frames_1, "resume appended no frames"
    # the resume point's fiber state is continued, not reset: the first
    # appended frame's fiber count can only differ by DI events of one step
    first_after = r2.load_frame(n_frames_1)
    assert first_after["time"] > last_before["time"]
    fibers_after = first_after["fibers"][1]
    # a site occupied before the resume either carries its surviving fiber
    # (length continued from the pre-resume value) or was freed by a
    # catastrophe and re-nucleated at min_length — never a reset mid-fiber
    min_length = 0.4
    by_site_before = {tuple(f["binding_site_"]): f["length_"]
                      for f in fibers_before}
    continued = 0
    for f in fibers_after:
        site = tuple(f["binding_site_"])
        if site not in by_site_before:
            continue
        if f["length_"] >= by_site_before[site] - 1e-12:
            continued += 1
        else:
            assert f["length_"] <= min_length + 0.25 * 0.05 + 1e-12, (
                "fiber length shrank without a re-nucleation")
    assert continued > 0, "no fiber state survived across the resume boundary"
    r2.close()

    # final frame simulated out to the extended horizon
    r3 = TrajectoryReader(traj)
    assert r3.times[-1] >= 0.55
    r3.close()


@pytest.mark.slow  # two e2e cli runs + a run->resume pair (~60 s)
def test_resume_into_runtime_ladder_rung_continues_bitwise(tmp_path):
    """skelly-scenario satellite: DI `--resume` under a non-identity
    `[runtime]` bucket ladder. A growth-only run (f_catastrophe = 0, so
    the live count tracks its geometric rung exactly) whose fiber capacity
    grew mid-flight is interrupted and resumed: the resume re-bucketizes
    the live fibers onto the SAME geometric rung the uninterrupted run
    occupies (`buckets.next_fiber_capacity` == the ladder's rung), the RNG
    stream restores its counters, and every appended frame is BYTE-equal
    to the uninterrupted run's — capacity padding is invisible to the
    physics and the wire."""
    def ladder_cfg(dirname, t_final):
        d = tmp_path / dirname
        d.mkdir(exist_ok=True)
        path = _di_config(d, t_final)
        cfg = open(path).read()
        with open(path, "w") as fh:
            # growth-only: catastrophes would let the live count fall below
            # its rung, and the uninterrupted capacity (which never
            # shrinks) would then diverge from the resume's re-bucketized
            # rung — draw shapes, and so the RNG stream, would split
            fh.write(cfg.replace("f_catastrophe = 0.5",
                                 "f_catastrophe = 0.0"))
            fh.write("\n[runtime]\nbucket_ladder = [-1]\n")
        return str(d), path

    # uninterrupted oracle to t=0.6
    full_dir, full_cfg = ladder_cfg("full", 0.6)
    precompute.precompute_from_config(full_cfg, verbose=False)
    cli.run(full_cfg)
    rf = TrajectoryReader(str(tmp_path / "full" / "skelly_sim.out"))
    full_frames = [rf.load_frame(i) for i in range(len(rf))]
    rf.close()
    counts = [len(f["fibers"][1]) for f in full_frames]
    assert max(counts) > 4, (
        "scene never outgrew the first ladder rungs — the test must cross "
        f"a capacity growth to mean anything (counts {counts})")

    # interrupted twin: run to t=0.3, extend, resume to 0.6
    part_dir, part_cfg = ladder_cfg("part", 0.3)
    precompute.precompute_from_config(part_cfg, verbose=False)
    cli.run(part_cfg)
    ladder_cfg("part", 0.6)
    cli.run(part_cfg, resume=True)

    rp = TrajectoryReader(str(tmp_path / "part" / "skelly_sim.out"))
    part_frames = [rp.load_frame(i) for i in range(len(rp))]
    rp.close()
    assert len(part_frames) == len(full_frames)
    for k, (a, b) in enumerate(zip(full_frames, part_frames)):
        assert a["time"] == b["time"], k
        fa, fb = a["fibers"][1], b["fibers"][1]
        assert len(fa) == len(fb), f"frame {k}: fiber count diverged"
        for f1, f2 in zip(fa, fb):
            for key in ("x_", "length_", "binding_site_", "tension_"):
                np.testing.assert_array_equal(
                    np.asarray(f1[key]), np.asarray(f2[key]),
                    err_msg=f"frame {k} field {key} not bitwise across "
                            "the ladder-rung resume")
