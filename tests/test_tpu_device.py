"""On-device accuracy gates: run the physics oracles on the real TPU.

The rest of the suite runs on a forced-CPU x64 backend (conftest). These
tests spawn subprocesses WITHOUT the CPU pin so the session's axon TPU
platform is used, and skip cleanly when no TPU is reachable (the tunnel can
be wedged for long stretches). This is the `@pytest.mark.tpu` deliverable of
round-2 verdict item 2: the reference's f64-class gates passing on hardware
whose LU is f32-only, via the mixed-precision solver.
"""

import json
import os
import subprocess
import sys

import pytest

# a healthy axon tunnel answers the tiny-matmul probe in seconds (client
# init blocking >60 s means wedged); a wedged one previously cost the
# 'not slow' tier a flat 60 s of waiting before the skips
_PROBE_TIMEOUT_S = int(os.environ.get("SKELLY_TPU_PROBE_TIMEOUT_S", "30"))
_probe_result = None


def _tpu_available() -> bool:
    """One cached probe per session; a wedged tunnel must not hang the suite."""
    global _probe_result
    if _probe_result is None:
        code = ("import jax, jax.numpy as jnp; "
                "x = jnp.ones((8, 8)); float((x @ x).sum()); "
                "print('BACKEND=' + jax.default_backend())")
        try:
            p = subprocess.run([sys.executable, "-c", code],
                               capture_output=True, text=True,
                               timeout=_PROBE_TIMEOUT_S, env=_tpu_env())
            _probe_result = "BACKEND=tpu" in (p.stdout or "")
        except Exception:
            _probe_result = False
    return _probe_result


def _tpu_env():
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)  # drop any CPU pin
    return env


_DRAG_SNIPPET = r"""
import json
import numpy as np
import jax
jax.config.update("jax_enable_x64", True)
import jax.numpy as jnp
from skellysim_tpu.bodies import bodies as bd
from skellysim_tpu.params import Params
from skellysim_tpu.periphery.precompute import precompute_body
from skellysim_tpu.system import System

eta, radius, force = 1.0, 0.5, 1.0
pre = precompute_body("sphere", 600, radius=radius)
bodies = bd.make_group(
    pre["node_positions_ref"], pre["node_normals_ref"], pre["node_weights"],
    position=np.zeros((1, 3)), external_force=np.array([[0.0, 0.0, force]]),
    radius=np.array([radius]), kind="sphere", dtype=jnp.float64)
params = Params(eta=eta, dt_initial=0.1, t_final=1.0, gmres_tol=1e-10,
                solver_precision="mixed", adaptive_timestep_flag=False)
system = System(params)
state = system.make_state(bodies=bodies)
new_state, solution, info = system.step(state)

r_eff = np.linalg.norm(np.asarray(pre["node_positions_ref"])[0])
v_theory = force / (6 * np.pi * eta * r_eff)
v_measured = float(new_state.bodies.velocity[0, 2])
print("RESULT=" + json.dumps({
    "backend": jax.default_backend(),
    "converged": bool(info.converged),
    "residual_true": float(info.residual_true),
    "drag_rel_err": abs(1 - v_measured / v_theory),
}))
"""


@pytest.mark.tpu
@pytest.mark.slow
def test_mixed_precision_drag_oracle_on_tpu():
    """Stokes-drag oracle at the reference's 1e-6 gate
    (`tests/combined/test_body_const_force.py:81`) with the mixed solver at
    gmres_tol 1e-10, executed on the real TPU."""
    if not _tpu_available():
        pytest.skip("no reachable TPU backend")
    p = subprocess.run([sys.executable, "-c", _DRAG_SNIPPET],
                       capture_output=True, text=True, timeout=540,
                       env=_tpu_env())
    assert p.returncode == 0, p.stderr[-2000:]
    line = next(ln for ln in p.stdout.splitlines() if ln.startswith("RESULT="))
    res = json.loads(line[len("RESULT="):])
    assert res["backend"] == "tpu"
    assert res["converged"]
    assert res["residual_true"] <= 1e-10
    assert res["drag_rel_err"] < 1e-6, res


_KERNEL_SNIPPET = r"""
import json
import numpy as np
import jax
jax.config.update("jax_enable_x64", True)
import jax.numpy as jnp
from skellysim_tpu.ops import kernels

rng = np.random.default_rng(5)
r_src = rng.uniform(-1, 1, (256, 3))
r_trg = rng.uniform(-1, 1, (199, 3))
f = rng.standard_normal((256, 3))

def host_oracle(r_src, r_trg, f_src):
    d = r_trg[:, None, :] - r_src[None, :, :]
    r2 = np.sum(d * d, axis=-1)
    rinv = np.where(r2 > 0, 1.0 / np.sqrt(np.where(r2 > 0, r2, 1.0)), 0.0)
    df = np.einsum("tsk,sk->ts", d, f_src)
    return (np.einsum("ts,sk->tk", rinv, f_src)
            + np.einsum("ts,tsk->tk", df * rinv**3, d)) / (8 * np.pi)

ref = host_oracle(r_src, r_trg, f)
dev = np.asarray(kernels.stokeslet_direct(
    jnp.asarray(r_src), jnp.asarray(r_trg), jnp.asarray(f), 1.0))
err = float(np.linalg.norm(dev - ref) / np.linalg.norm(ref))
print("RESULT=" + json.dumps({"backend": jax.default_backend(), "err": err}))
"""


@pytest.mark.tpu
def test_kernel_agreement_gate_on_tpu():
    """f64 stokeslet on the TPU vs the single-threaded host oracle at the
    reference's 5e-9 backend-agreement gate
    (`/root/reference/tests/core/kernel_test.cpp:93`)."""
    if not _tpu_available():
        pytest.skip("no reachable TPU backend")
    p = subprocess.run([sys.executable, "-c", _KERNEL_SNIPPET],
                       capture_output=True, text=True, timeout=540,
                       env=_tpu_env())
    assert p.returncode == 0, p.stderr[-2000:]
    line = next(ln for ln in p.stdout.splitlines() if ln.startswith("RESULT="))
    res = json.loads(line[len("RESULT="):])
    assert res["backend"] == "tpu"
    assert res["err"] <= 5e-9, res


_PALLAS_SNIPPET = r"""
import json
import numpy as np
import jax
import jax.numpy as jnp
from skellysim_tpu.ops import kernels

rng = np.random.default_rng(11)
r = jnp.asarray(rng.uniform(-2, 2, (2048, 3)), jnp.float32)
f = jnp.asarray(rng.standard_normal((2048, 3)), jnp.float32)
S = jnp.asarray(rng.standard_normal((2048, 3, 3)), jnp.float32)
u_p = np.asarray(kernels.stokeslet_direct(r, r, f, 1.3, impl="pallas"))
u_x = np.asarray(kernels.stokeslet_direct(r, r, f, 1.3))
e1 = float(np.linalg.norm(u_p - u_x) / np.linalg.norm(u_x))
s_p = np.asarray(kernels.stresslet_direct(r, r, S, 1.3, impl="pallas"))
s_x = np.asarray(kernels.stresslet_direct(r, r, S, 1.3))
e2 = float(np.linalg.norm(s_p - s_x) / np.linalg.norm(s_x))
print("RESULT=" + json.dumps({"backend": jax.default_backend(),
                              "stokeslet_err": e1, "stresslet_err": e2}))
"""


@pytest.mark.tpu
def test_pallas_mosaic_agreement_on_tpu():
    """The Mosaic-compiled Pallas tiles vs the XLA kernels on the real chip
    (the interpret-mode comparisons in test_pallas_kernels.py cover CPU;
    this is the compiled-lowering half of the backend-consistency matrix).
    f32 accumulation over 2048 sources bounds the disagreement ~1e-6."""
    if not _tpu_available():
        pytest.skip("no reachable TPU backend")
    p = subprocess.run([sys.executable, "-c", _PALLAS_SNIPPET],
                       capture_output=True, text=True, timeout=540,
                       env=_tpu_env())
    assert p.returncode == 0, p.stderr[-2000:]
    line = next(ln for ln in p.stdout.splitlines() if ln.startswith("RESULT="))
    res = json.loads(line[len("RESULT="):])
    assert res["backend"] == "tpu"
    assert res["stokeslet_err"] < 1e-5, res
    assert res["stresslet_err"] < 1e-5, res
