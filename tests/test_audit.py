"""skelly-audit engine tests (`skellysim_tpu.audit`).

Each check gets flag / pass / suppress coverage on *synthetic* programs
(tiny jits lowered in-process — the real entry-point matrix is expensive to
build, so the fast tier exercises the engine on small fixtures plus the
bare-GMRES program, and the multi-device lowering fixtures ride the slow
tier). The contract-drift case pins the acceptance property: perturbing a
contract makes the auditor exit non-zero.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from skellysim_tpu.audit import checks as ck
from skellysim_tpu.audit import engine
from skellysim_tpu.audit.cli import main as audit_main
from skellysim_tpu.audit.registry import AuditProgram, built_from
from skellysim_tpu.config import toml_io


def _prog(fn, *args, name="synthetic", probe=None):
    return AuditProgram(
        name=name, layer="test", summary="synthetic",
        build=lambda: built_from(jax.jit(fn), *args), retrace_probe=probe)


def _audit(prog, contract, checks=None):
    return engine.run_program_audit(prog, contract=contract, checks=checks)


def _ids(findings):
    return sorted(f.check for f in findings)


# ------------------------------------------------------ collective-contract

@pytest.fixture(scope="module")
def psum_prog():
    from skellysim_tpu.parallel.compat import shard_map
    from skellysim_tpu.parallel.mesh import FIBER_AXIS, make_mesh

    mesh = make_mesh(8)
    from jax.sharding import PartitionSpec as P

    def fn(x):
        return shard_map(lambda s: jax.lax.psum(s, FIBER_AXIS), mesh=mesh,
                         in_specs=P(FIBER_AXIS), out_specs=P())(x)

    return _prog(fn, jnp.zeros(16, jnp.float64))


def test_collectives_flag_uncontracted_and_drift(psum_prog):
    f = _audit(psum_prog, {}, checks=["collective-contract"])
    assert _ids(f) == ["collective-contract"]
    assert "uncontracted" in f[0].message

    good = {"collectives": {"all_reduce": {"count": 1, "max_elems": 2}}}
    assert _audit(psum_prog, good, checks=["collective-contract"]) == []

    drift = {"collectives": {"all_reduce": {"count": 3, "max_elems": 2}}}
    f = _audit(psum_prog, drift, checks=["collective-contract"])
    assert len(f) == 1 and "count drifted" in f[0].message

    bound = {"collectives": {"all_reduce": {"count": 1, "max_elems": 1}}}
    f = _audit(psum_prog, bound, checks=["collective-contract"])
    assert len(f) == 1 and "over the contract bound" in f[0].message


def test_collectives_flag_stale_contract_entry():
    prog = _prog(lambda x: x * 2.0, jnp.zeros(4, jnp.float64))
    stale = {"collectives": {"all_gather": {"count": 2}}}
    f = _audit(prog, stale, checks=["collective-contract"])
    assert len(f) == 1 and "stale contract" in f[0].message
    # bound-only entries rot silently once the op vanishes: also stale
    bound_only = {"collectives": {"all_gather": {"max_elems": 100}}}
    f = _audit(prog, bound_only, checks=["collective-contract"])
    assert len(f) == 1 and "stale contract" in f[0].message


def test_collectives_require_a_count_pin(psum_prog):
    # a contracted op present in the program must pin its static count
    bound_only = {"collectives": {"all_reduce": {"max_elems": 2}}}
    f = _audit(psum_prog, bound_only, checks=["collective-contract"])
    assert len(f) == 1 and "no `count` pin" in f[0].message


def test_collectives_suppressed_with_contract_entry(psum_prog):
    contract = {"suppress": [{
        "check": "collective-contract", "match": "uncontracted collective",
        "reason": "fixture: deliberate psum under test"}]}
    assert _audit(psum_prog, contract, checks=["collective-contract"]) == []


# --------------------------------------------------------------- dtype-flow

def _promoting(x):
    # a deliberate narrow->wide edge on the traced path
    return x.astype(jnp.float64) * 2.0


def test_dtype_flags_promotion_edge():
    prog = _prog(_promoting, jnp.zeros(4, jnp.float32))
    f = _audit(prog, {}, checks=["dtype-flow"])
    assert len(f) == 1 and "float32->float64" in f[0].message

    pinned = {"dtype": {"promotions": {"float32->float64": 1}}}
    assert _audit(prog, pinned, checks=["dtype-flow"]) == []

    drifted = {"dtype": {"promotions": {"float32->float64": 2}}}
    f = _audit(prog, drifted, checks=["dtype-flow"])
    assert len(f) == 1 and "count drifted" in f[0].message


def test_dtype_flags_stale_promotion_pin():
    prog = _prog(lambda x: x + 1.0, jnp.zeros(4, jnp.float64))
    stale = {"dtype": {"promotions": {"float32->float64": 1}}}
    f = _audit(prog, stale, checks=["dtype-flow"])
    assert len(f) == 1 and "stale contract" in f[0].message


def test_dtype_suppressed_via_contract():
    prog = _prog(_promoting, jnp.zeros(4, jnp.float32))
    contract = {"suppress": [{
        "check": "dtype-flow", "match": "float32->float64",
        "reason": "fixture: the refinement-merge pattern"}]}
    assert _audit(prog, contract, checks=["dtype-flow"]) == []


# ---------------------------------------------------------------- host-sync

def _callback_prog():
    def fn(x):
        y = jax.pure_callback(
            lambda a: np.asarray(a), jax.ShapeDtypeStruct(x.shape, x.dtype), x)
        return y + x

    return _prog(fn, jnp.zeros(3, jnp.float64))


def test_host_sync_flags_pure_callback():
    f = _audit(_callback_prog(), {}, checks=["host-sync"])
    assert len(f) == 1 and "pure_callback" in f[0].message


def test_host_sync_allowed_by_contract_and_stale_allowance():
    allowed = {"host_sync": {"allowed_callbacks": ["pure_callback"]}}
    assert _audit(_callback_prog(), allowed, checks=["host-sync"]) == []

    clean = _prog(lambda x: x * 2.0, jnp.zeros(3, jnp.float64))
    f = _audit(clean, allowed, checks=["host-sync"])
    assert len(f) == 1 and "stale contract" in f[0].message


# ----------------------------------------------------------------- donation

def test_donation_check_both_directions():
    x = jnp.zeros(8, jnp.float64)

    donating = AuditProgram(
        name="synthetic", layer="test", summary="",
        build=lambda: built_from(jax.jit(lambda v: v + 1.0,
                                         donate_argnums=(0,)), x))
    plain = _prog(lambda v: v + 1.0, x)

    assert _audit(donating, {"donation": {"donated": True}},
                  checks=["donation"]) == []
    f = _audit(donating, {"donation": {"donated": False}},
               checks=["donation"])
    assert len(f) == 1 and "rollback" in f[0].message

    assert _audit(plain, {"donation": {"donated": False}},
                  checks=["donation"]) == []
    f = _audit(plain, {"donation": {"donated": True}}, checks=["donation"])
    assert len(f) == 1 and "no aliasing marker" in f[0].message


# ----------------------------------------------------------- retrace-budget

def test_retrace_budget_flags_over_budget_and_missing_probe():
    x = jnp.zeros(2, jnp.float64)
    over = _prog(lambda v: v, x, probe=lambda: 3)
    f = _audit(over, {"retrace": {"max_traces": 1}},
               checks=["retrace-budget"])
    assert len(f) == 1 and "traced 3x" in f[0].message

    ok = _prog(lambda v: v, x, probe=lambda: 1)
    assert _audit(ok, {"retrace": {"max_traces": 1}},
                  checks=["retrace-budget"]) == []

    no_probe = _prog(lambda v: v, x)
    f = _audit(no_probe, {"retrace": {"max_traces": 1}},
               checks=["retrace-budget"])
    assert len(f) == 1 and "no retrace probe" in f[0].message


# -------------------------------------------------------------- replication

def _shmap_prog(inner, in_specs, out_specs, *args, name="synthetic"):
    """A shard_map program on the 8-device mesh, registered audit-style."""
    from skellysim_tpu.parallel.compat import shard_map
    from skellysim_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(8)

    def fn(*xs):
        return shard_map(inner, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, check_vma=False)(*xs)

    return _prog(fn, *args, name=name)


def _fib_P():
    from jax.sharding import PartitionSpec as P

    from skellysim_tpu.parallel.mesh import FIBER_AXIS
    return FIBER_AXIS, P


#: the four documented anti-patterns (ISSUE 11) as tiny shard_map programs,
#: each next to its disciplined twin — these pin the analyzer's SEMANTICS
#: independently of the real registered programs
def _divergent_while_prog(psum_pred: bool):
    ax, P = _fib_P()

    def inner(s):
        def cond(c):
            i, v = c
            local = jnp.sum(v)
            quant = jax.lax.psum(local, ax) if psum_pred else local
            return (i < 3) & (quant < 100.0)

        def body(c):
            i, v = c
            return i + 1, v + jax.lax.psum(v, ax)

        return jax.lax.while_loop(cond, body, (jnp.int32(0), s))[1]

    return _shmap_prog(inner, (P(ax),), P(ax), jnp.zeros(16, jnp.float64))


def _collective_under_cond_prog():
    ax, P = _fib_P()

    def inner(s):
        return jax.lax.cond(jnp.sum(s) > 0.0,           # local → varying
                            lambda v: jax.lax.psum(v, ax), lambda v: v, s)

    return _shmap_prog(inner, (P(ax),), P(ax), jnp.zeros(16, jnp.float64))


def _unreduced_output_prog(reduced: bool):
    ax, P = _fib_P()

    def inner(s):
        total = jnp.sum(s)                               # per-shard partial
        return jax.lax.psum(total, ax) if reduced else total

    return _shmap_prog(inner, (P(ax),), P(), jnp.zeros(16, jnp.float64))


def _ring_accumulation_prog(psum_closed: bool):
    ax, P = _fib_P()

    def inner(s):
        if psum_closed:
            return jax.lax.psum(jnp.sum(s), ax)          # the discipline
        perm = [(i, (i + 1) % 8) for i in range(8)]
        acc, blk = s, s
        for _ in range(7):                               # the anti-pattern
            blk = jax.lax.ppermute(blk, ax, perm)
            acc = acc + blk
        return jnp.sum(acc)

    return _shmap_prog(inner, (P(ax),), P(), jnp.zeros(16, jnp.float64))


def _rep_contract(replicated: int, varying: int):
    """A correct [replication] pin for the one-in/one-out fixtures above."""
    return {"replication": {"mesh_axes": ["fib"], "replicated_outputs":
                            replicated, "varying_outputs": varying}}


def test_replication_flags_divergent_while_and_passes_psum_pred():
    f = _audit(_divergent_while_prog(psum_pred=False),
               _rep_contract(0, 1), checks=["replication"])
    assert {x.check for x in f} == {"replication"}
    msgs = " | ".join(x.message for x in f)
    assert "divergent-control" in msgs
    assert "collective-under-divergence" in msgs
    assert _audit(_divergent_while_prog(psum_pred=True),
                  _rep_contract(0, 1), checks=["replication"]) == []


def test_replication_flags_axis_index_derived_predicate():
    """axis_index is varying BY DEFINITION: a trip count keyed on the shard
    id (`i < axis_index`) is a real per-shard divergence with a psum in the
    body — the review-found soundness hole, regression-pinned."""
    ax, P = _fib_P()

    def inner(s):
        def cond(c):
            return c[0] < jax.lax.axis_index(ax)

        def body(c):
            return c[0] + 1, c[1] + jax.lax.psum(c[1], ax)

        return jax.lax.while_loop(cond, body, (jnp.int32(0), s))[1]

    prog = _shmap_prog(inner, (P(ax),), P(ax), jnp.zeros(16, jnp.float64))
    f = _audit(prog, _rep_contract(0, 1), checks=["replication"])
    msgs = " | ".join(x.message for x in f)
    assert "divergent-control" in msgs
    assert "collective-under-divergence" in msgs
    # and axis_index itself is NOT a collective: outside any divergence it
    # is legal, it just must propagate as varying
    def inner_ok(s):
        return s * (1.0 + jax.lax.axis_index(ax).astype(s.dtype))

    ok = _shmap_prog(inner_ok, (P(ax),), P(ax), jnp.zeros(16, jnp.float64))
    assert _audit(ok, _rep_contract(0, 1), checks=["replication"]) == []


def test_replication_flags_collective_under_varying_cond():
    f = _audit(_collective_under_cond_prog(), _rep_contract(0, 1),
               checks=["replication"])
    msgs = " | ".join(x.message for x in f)
    assert "collective-under-divergence" in msgs
    assert "divergent-control" in msgs     # the cond-of-collectives variant


def test_replication_flags_unreduced_replicated_output():
    f = _audit(_unreduced_output_prog(reduced=False), _rep_contract(1, 0),
               checks=["replication"])
    assert len(f) == 1 and "unreduced-replicated-output" in f[0].message
    assert _audit(_unreduced_output_prog(reduced=True), _rep_contract(1, 0),
                  checks=["replication"]) == []


def test_replication_flags_ring_order_accumulation():
    f = _audit(_ring_accumulation_prog(psum_closed=False),
               _rep_contract(1, 0), checks=["replication"])
    assert len(f) == 1 and "ring-order-accumulation" in f[0].message
    assert "different ring order" in f[0].message
    assert _audit(_ring_accumulation_prog(psum_closed=True),
                  _rep_contract(1, 0), checks=["replication"]) == []


def _two_axis_prog(full_reduce: bool):
    """(member, fiber) two-axis shard_map — ROADMAP item 1 readiness.

    Three outputs span the varying-over(axes) lattice: varying over BOTH
    axes, varying over member only (fiber axis psum'd away), and fully
    reduced. With ``full_reduce=False`` the third output psums only the
    fiber axis while declaring P(): the residue varying over {member}
    must flag — a single-axis analyzer would call it replicated."""
    from jax.sharding import PartitionSpec as P

    from skellysim_tpu.parallel.compat import shard_map
    from skellysim_tpu.parallel.mesh import (FIBER_AXIS, MEMBER_AXIS,
                                             make_2d_mesh)

    mesh = make_2d_mesh(2, 4)

    def inner(s):
        both = s * 2.0
        mem = jax.lax.psum(jnp.sum(s, axis=1), FIBER_AXIS)
        tot = jnp.sum(s)
        tot = jax.lax.psum(
            tot, (MEMBER_AXIS, FIBER_AXIS) if full_reduce else FIBER_AXIS)
        return both, mem, tot

    def fn(x):
        return shard_map(
            inner, mesh=mesh,
            in_specs=(P(MEMBER_AXIS, FIBER_AXIS),),
            out_specs=(P(MEMBER_AXIS, FIBER_AXIS), P(MEMBER_AXIS), P()),
            check_vma=False)(x)

    return _prog(fn, jnp.zeros((4, 16), jnp.float64), name="synthetic2d")


def _two_axis_contract():
    return {"replication": {"mesh_axes": ["fib", "member"],
                            "replicated_outputs": 1, "varying_outputs": 2}}


def test_replication_two_axis_lattice_round_trip():
    """The disciplined (member, fiber) program is clean under a two-axis
    [replication] pin, and --dump-contract emits both mesh axes."""
    prog = _two_axis_prog(full_reduce=True)
    assert _audit(prog, _two_axis_contract(), checks=["replication"]) == []

    base = AuditProgram(name="dumprep2d", layer="test", summary="synthetic",
                        build=prog.build)
    data = toml_io.loads(engine.dump_contract(base))
    assert data["replication"] == {"mesh_axes": ["fib", "member"],
                                   "replicated_outputs": 1,
                                   "varying_outputs": 2}


def test_replication_two_axis_partial_reduction_flags():
    """psum over the fiber axis alone does NOT make a value replicated on
    a 2-D mesh: the member-axis residue must be tracked per axis."""
    f = _audit(_two_axis_prog(full_reduce=False), _two_axis_contract(),
               checks=["replication"])
    assert len(f) == 1, [x.message for x in f]
    assert "unreduced-replicated-output" in f[0].message
    assert "member" in f[0].message and "fib" not in f[0].message


def test_replication_contract_surface_drift_and_staleness():
    prog = _unreduced_output_prog(reduced=True)
    # a sharded program must carry the section
    f = _audit(prog, {}, checks=["replication"])
    assert len(f) == 1 and "no [replication] section" in f[0].message
    # count drift: an output moved across the replicated/sharded boundary
    f = _audit(prog, _rep_contract(2, 0), checks=["replication"])
    assert len(f) == 1 and "replicated_outputs drifted" in f[0].message
    # missing pins are findings (a pin-less section would rot silently)
    f = _audit(prog, {"replication": {"mesh_axes": ["fib"]}},
               checks=["replication"])
    assert len(f) == 2 and all("pin" in x.message for x in f)
    # axis drift
    f = _audit(prog, {"replication": {"mesh_axes": ["member"],
                                      "replicated_outputs": 1,
                                      "varying_outputs": 0}},
               checks=["replication"])
    assert len(f) == 1 and "mesh axes drifted" in f[0].message
    # and a single-device program with a pinned section is stale
    plain = _prog(lambda x: x + 1.0, jnp.zeros(4, jnp.float64))
    f = _audit(plain, _rep_contract(1, 0), checks=["replication"])
    assert len(f) == 1 and "stale contract" in f[0].message


def test_replication_violations_gate_the_cli_exit_code(tmp_path, monkeypatch):
    """The acceptance pin: each seeded anti-pattern flips `--check
    replication` to exit 1; the disciplined twins exit 0."""
    import skellysim_tpu.audit.programs as programs_mod

    def rc(prog, contract):
        monkeypatch.setattr(programs_mod, "all_programs", lambda: [prog])
        monkeypatch.setattr(engine, "CONTRACT_DIR", str(tmp_path))
        path = tmp_path / f"{prog.name}.toml"
        path.write_text(toml_io.dumps(dict({"program": {"name": prog.name}},
                                           **contract)))
        return audit_main(["--check", "replication"])

    assert rc(_divergent_while_prog(False), _rep_contract(0, 1)) == 1
    assert rc(_collective_under_cond_prog(), _rep_contract(0, 1)) == 1
    assert rc(_unreduced_output_prog(False), _rep_contract(1, 0)) == 1
    assert rc(_ring_accumulation_prog(False), _rep_contract(1, 0)) == 1
    assert rc(_divergent_while_prog(True), _rep_contract(0, 1)) == 0
    assert rc(_unreduced_output_prog(True), _rep_contract(1, 0)) == 0


def test_replication_suppression_matches_on_kind():
    contract = dict(_rep_contract(1, 0), suppress=[{
        "check": "replication", "match": "ring-order-accumulation",
        "reason": "fixture: deliberate ring accumulation under test"}])
    assert _audit(_ring_accumulation_prog(psum_closed=False), contract,
                  checks=["replication"]) == []


def test_replication_dump_contract_roundtrips():
    base = _unreduced_output_prog(reduced=True)
    prog = AuditProgram(name="dumprep", layer="test", summary="synthetic",
                        build=base.build)
    text = engine.dump_contract(prog)
    data = toml_io.loads(text)
    assert data["replication"] == {"mesh_axes": ["fib"],
                                   "replicated_outputs": 1,
                                   "varying_outputs": 0}


# --------------------------------------------------------------------- mask

def _mask_args():
    """One dict arg: a padded (8, 3) field with rows 5..7 dead."""
    return ({"x": jnp.ones((8, 3), jnp.float64),
             "active": jnp.arange(8, dtype=jnp.int32) < 5},)


def _mask_prog(fn, name="synthetic"):
    return _prog(fn, *_mask_args(), name=name)


def _mask_contract(outputs):
    """A `[mask]` section declaring the fiber capacity axis over the whole
    first arg, plus the given `[mask.outputs]` pin table."""
    return {"mask": {
        "axes": [{"name": "fiber", "mask": "0.active", "scope": "0",
                  "dim": 0}],
        "outputs": outputs}}


#: each finding kind as a tiny violation program next to its disciplined
#: twin — these pin the analyzer's SEMANTICS independently of the real
#: registered programs (same pattern as the replication fixtures above)
def _escape_prog():
    # x[0] + x[3]: the padded dim is indexed away, so pad garbage lands in
    # live entries with nothing left to attribute it to
    return _mask_prog(lambda d: d["x"][0] + d["x"][3])


def _nan_unsafe_prog(disciplined: bool):
    # 1/x can be inf; `* mask` then mints 0 * inf = NaN at dead slots —
    # where-selection is the bitwise-identical-for-finite fix
    if disciplined:
        return _mask_prog(
            lambda d: jnp.where(d["active"][:, None], 1.0 / d["x"], 0.0))
    return _mask_prog(lambda d: (1.0 / d["x"]) * d["active"][:, None])


def _reduction_prog(disciplined: bool):
    if disciplined:
        return _mask_prog(lambda d: jnp.sum(
            jnp.where(d["active"][:, None], d["x"], 0.0), axis=0))
    return _mask_prog(lambda d: jnp.sum(d["x"], axis=0))


def _argreduce_prog(disciplined: bool):
    if disciplined:
        return _mask_prog(lambda d: jnp.argmax(
            jnp.where(d["active"], jnp.sum(d["x"], axis=1), -jnp.inf)))
    return _mask_prog(lambda d: jnp.argmax(jnp.sum(d["x"], axis=1)))


def _mask_kinds(findings):
    return sorted({m.split(":")[0] for m in (f.message for f in findings)})


def test_mask_flags_pad_escape():
    f = _audit(_escape_prog(), _mask_contract({"result": "live-only"}),
               checks=["mask"])
    assert _mask_kinds(f) == ["pad-escape"], [x.message for x in f]


def test_mask_flags_nan_unsafe_neutralization():
    f = _audit(_nan_unsafe_prog(False),
               _mask_contract({"result": "pad-passthrough"}),
               checks=["mask"])
    assert _mask_kinds(f) == ["nan-unsafe-neutralization"]
    assert _audit(_nan_unsafe_prog(True),
                  _mask_contract({"result": "pad-exact-zero"}),
                  checks=["mask"]) == []


def test_mask_flags_unmasked_reduction():
    f = _audit(_reduction_prog(False),
               _mask_contract({"result": "live-only"}), checks=["mask"])
    assert _mask_kinds(f) == ["pad-escape", "unmasked-reduction"]
    assert _audit(_reduction_prog(True),
                  _mask_contract({"result": "live-only"}),
                  checks=["mask"]) == []


def test_mask_flags_unsentineled_argreduce():
    f = _audit(_argreduce_prog(False),
               _mask_contract({"result": "live-only"}), checks=["mask"])
    assert _mask_kinds(f) == ["pad-escape", "unsentineled-argreduce"]
    assert _audit(_argreduce_prog(True),
                  _mask_contract({"result": "live-only"}),
                  checks=["mask"]) == []


def test_mask_contract_surface_paths():
    clean = _mask_prog(
        lambda d: jnp.where(d["active"][:, None], d["x"], 0.0))

    f = _audit(clean, {}, checks=["mask"])
    assert len(f) == 1 and "no [mask] section" in f[0].message

    f = _audit(clean, _mask_contract({}), checks=["mask"])
    assert len(f) == 1 and "no [mask.outputs] pin" in f[0].message

    f = _audit(clean, _mask_contract({"result": "pad-zeroish"}),
               checks=["mask"])
    assert len(f) == 1 and "unknown pad class" in f[0].message

    f = _audit(clean, _mask_contract({"result": "live-only"}),
               checks=["mask"])
    assert len(f) == 1 and "pad class drifted" in f[0].message

    f = _audit(clean, _mask_contract({"result": "pad-exact-zero",
                                      "ghost": "live-only"}),
               checks=["mask"])
    assert len(f) == 1 and "stale pin" in f[0].message

    f = _audit(clean, {"mask": {"axes": [],
                                "outputs": {"result": "live-only"}}},
               checks=["mask"])
    assert len(f) == 1 and "stale [mask.outputs] table" in f[0].message

    f = _audit(clean, {"mask": {"axes": [{"name": "fiber"}]}},
               checks=["mask"])
    assert len(f) == 1 and "needs both `name` and `mask`" in f[0].message


def test_mask_suppression_used_and_unused():
    sup = [{"check": "mask", "match": "nan-unsafe-neutralization",
            "reason": "fixture: deliberate multiplicative mask under test"}]
    contract = dict(_mask_contract({"result": "pad-passthrough"}),
                    suppress=sup)
    assert _audit(_nan_unsafe_prog(False), contract, checks=["mask"]) == []

    stale = dict(_mask_contract({"result": "pad-exact-zero"}), suppress=sup)
    f = _audit(_nan_unsafe_prog(True), stale, checks=["mask"])
    assert len(f) == 1 and "unused suppression" in f[0].message


def test_mask_violations_gate_the_cli_exit_code(tmp_path, monkeypatch):
    """The acceptance pin: every seeded violation flips `--check mask` to
    exit 1; the disciplined twins exit 0."""
    import skellysim_tpu.audit.kernels as kernels_mod
    import skellysim_tpu.audit.programs as programs_mod

    def rc(prog, contract):
        monkeypatch.setattr(programs_mod, "all_programs", lambda: [prog])
        monkeypatch.setattr(kernels_mod, "all_kernels", lambda: [])
        monkeypatch.setattr(engine, "CONTRACT_DIR", str(tmp_path))
        path = tmp_path / f"{prog.name}.toml"
        path.write_text(toml_io.dumps(dict({"program": {"name": prog.name}},
                                           **contract)))
        return audit_main(["--check", "mask"])

    live = _mask_contract({"result": "live-only"})
    assert rc(_escape_prog(), live) == 1
    assert rc(_nan_unsafe_prog(False),
              _mask_contract({"result": "pad-passthrough"})) == 1
    assert rc(_reduction_prog(False), live) == 1
    assert rc(_argreduce_prog(False), live) == 1
    assert rc(_nan_unsafe_prog(True),
              _mask_contract({"result": "pad-exact-zero"})) == 0
    assert rc(_reduction_prog(True), live) == 0
    assert rc(_argreduce_prog(True), live) == 0


def test_mask_dump_contract_emits_observed_pins(tmp_path, monkeypatch):
    """--dump-contract re-reads the EXISTING axes declaration (declaring a
    capacity axis is a human decision) and emits the analyzer-proven class
    for every output under it."""
    monkeypatch.setattr(engine, "CONTRACT_DIR", str(tmp_path))
    prog = _mask_prog(
        lambda d: jnp.where(d["active"][:, None], d["x"], 0.0),
        name="dumpmask")
    (tmp_path / "dumpmask.toml").write_text(toml_io.dumps(
        dict({"program": {"name": "dumpmask"}}, **_mask_contract({}))))
    data = toml_io.loads(engine.dump_contract(prog))
    assert data["mask"]["outputs"]["result"] == "pad-exact-zero"


def test_mask_pad_exact_zero_pin_matches_runtime_bitwise():
    """The runtime cross-check: the class the analyzer proves for the
    where-select twin is exactly what executing the program shows — dead
    rows come out bitwise +0.0 even when their inputs hold inf/NaN
    garbage (the property test_buckets pins for the real step programs)."""
    fn = lambda d: jnp.where(d["active"][:, None], 1.0 / d["x"], 0.0)
    bp = built_from(jax.jit(fn), *_mask_args())
    report = ck.mask_summary(
        bp, ck.mask_axes_from_contract(
            _mask_contract({})["mask"], "x")[0])[0]
    assert dict(report.classes)["result"] == "pad-exact-zero"

    (arg,) = _mask_args()
    x = arg["x"].at[5].set(jnp.inf).at[6].set(jnp.nan).at[7].set(0.0)
    out = jax.jit(fn)({"x": x, "active": arg["active"]})
    dead = np.asarray(out)[5:]
    assert (np.signbit(dead) == False).all()  # noqa: E712 — bitwise +0.0
    assert (np.asarray(dead) == 0.0).all()


def test_mask_real_step_pins_match_bitwise_padding_tests():
    """The shipped contracts' pad-class pins encode the same invariants
    the runtime padding-parity tests assert (test_buckets): padded state
    rows ride through bitwise-unchanged, the refreshed active mask is
    exact zeros at dead slots, and the solution vector is live-only."""
    for name in ("step_single", "step_flight", "step_mixed"):
        contract, findings = engine.load_contract(name)
        assert findings == [], name
        pins = contract["mask"]["outputs"]
        assert pins["0.fibers.x"] == "pad-passthrough", name
        assert pins["0.fibers.tension"] == "pad-passthrough", name
        assert pins["0.fibers.active"] == "pad-exact-zero", name
        assert pins["1"] == "live-only", name
    contract, findings = engine.load_contract("ensemble_step")
    assert findings == []
    assert contract["mask"]["outputs"]["0.states.fibers.x"] == \
        "pad-passthrough"


# ----------------------------------------------- contract file / suppression

def test_contract_validation_findings(tmp_path, monkeypatch):
    monkeypatch.setattr(engine, "CONTRACT_DIR", str(tmp_path))
    _, f = engine.load_contract("nope")
    assert len(f) == 1 and "no contract file" in f[0].message

    (tmp_path / "bad.toml").write_text(
        '[program]\nname = "other"\n[typo_section]\nx = 1\n'
        '[[suppress]]\ncheck = "dtype-flow"\nmatch = "x"\n'
        '[[suppress]]\ncheck = "dtype-flow"\nmatch = ""\nreason = "r"\n')
    _, f = engine.load_contract("bad")
    msgs = " | ".join(x.message for x in f)
    assert "unknown contract section" in msgs
    assert "copy-paste drift" in msgs
    assert "missing its reason" in msgs
    # an empty match would blanket-suppress its whole check
    assert "non-empty `match`" in msgs


def test_empty_suppress_match_never_suppresses():
    prog = _prog(_promoting, jnp.zeros(4, jnp.float32))
    blanket = {"suppress": [{"check": "dtype-flow", "match": "",
                             "reason": "illegitimate blanket"}]}
    # the finding survives (and the dead entry is itself reported unused)
    f = _audit(prog, blanket, checks=["dtype-flow"])
    assert sorted(x.check for x in f) == ["contract", "dtype-flow"]
    assert any("float32->float64" in x.message for x in f)


def test_unused_suppression_is_a_finding():
    prog = _prog(lambda x: x + 1.0, jnp.zeros(2, jnp.float64))
    contract = {"mask": {"axes": []},
                "suppress": [{"check": "dtype-flow", "match": "never-hits",
                              "reason": "stale"}]}
    f = _audit(prog, contract)
    assert len(f) == 1 and "unused suppression" in f[0].message
    # a check-filtered run must not flag suppressions for skipped checks
    assert _audit(prog, contract, checks=["host-sync"]) == []


def test_dump_contract_roundtrips_through_toml():
    prog = _prog(_promoting, jnp.zeros(4, jnp.float32), name="dumpme")
    text = engine.dump_contract(prog)
    data = toml_io.loads(text)  # the quoted "float32->float64" key must parse
    assert data["program"]["name"] == "dumpme"
    assert data["dtype"]["promotions"]["float32->float64"] == 1


# ------------------------------------------------- the real program matrix

def test_gmres_program_is_contract_clean_end_to_end():
    """The solver-layer entry point through the real tree contract,
    retrace probe included (cheap: a 64x64 f32 solve)."""
    assert audit_main(["--program", "gmres_f32"]) == 0


def test_perturbed_contract_fails_the_cli(tmp_path, monkeypatch):
    """The acceptance property: perturbing a contract file flips the CLI
    to a non-zero exit."""
    real = engine.contract_path("gmres_f32")
    perturbed = toml_io.load(real)
    perturbed["collectives"] = {"all_gather": {"count": 1}}
    (tmp_path / "gmres_f32.toml").write_text(toml_io.dumps(perturbed))
    monkeypatch.setattr(engine, "CONTRACT_DIR", str(tmp_path))
    assert audit_main(["--program", "gmres_f32", "--check",
                       "collective-contract"]) == 1


def test_cli_usage_paths():
    assert audit_main(["--list-checks"]) == 0
    assert audit_main(["--list-programs"]) == 0
    assert audit_main(["--program", "bogus"]) == 2
    assert audit_main(["--check", "bogus"]) == 2


@pytest.mark.slow
def test_spmd_ladder_is_contract_clean():
    """d2/d4 lowering fixtures (d8 is pinned per-commit by test_spmd's
    wrapper): the collective inventory scales exactly as contracted —
    density-bounded all_gather at every mesh size, ppermute blocks halving
    with D. Slow: two full coupled shard_map lowerings."""
    from skellysim_tpu.audit.programs import get_program

    for name in ("step_spmd_d2", "step_spmd_d4"):
        prog = get_program(name)
        assert engine.run_program_audit(prog) == [], name


@pytest.mark.slow
def test_full_matrix_is_contract_clean():
    """`python -m skellysim_tpu.audit` over the whole registered matrix —
    the CI gate's exact invocation, exit 0 on this tree."""
    assert audit_main([]) == 0
