"""skelly-audit engine tests (`skellysim_tpu.audit`).

Each check gets flag / pass / suppress coverage on *synthetic* programs
(tiny jits lowered in-process — the real entry-point matrix is expensive to
build, so the fast tier exercises the engine on small fixtures plus the
bare-GMRES program, and the multi-device lowering fixtures ride the slow
tier). The contract-drift case pins the acceptance property: perturbing a
contract makes the auditor exit non-zero.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from skellysim_tpu.audit import checks as ck
from skellysim_tpu.audit import engine
from skellysim_tpu.audit.cli import main as audit_main
from skellysim_tpu.audit.registry import AuditProgram, built_from
from skellysim_tpu.config import toml_io


def _prog(fn, *args, name="synthetic", probe=None):
    return AuditProgram(
        name=name, layer="test", summary="synthetic",
        build=lambda: built_from(jax.jit(fn), *args), retrace_probe=probe)


def _audit(prog, contract, checks=None):
    return engine.run_program_audit(prog, contract=contract, checks=checks)


def _ids(findings):
    return sorted(f.check for f in findings)


# ------------------------------------------------------ collective-contract

@pytest.fixture(scope="module")
def psum_prog():
    from skellysim_tpu.parallel.compat import shard_map
    from skellysim_tpu.parallel.mesh import FIBER_AXIS, make_mesh

    mesh = make_mesh(8)
    from jax.sharding import PartitionSpec as P

    def fn(x):
        return shard_map(lambda s: jax.lax.psum(s, FIBER_AXIS), mesh=mesh,
                         in_specs=P(FIBER_AXIS), out_specs=P())(x)

    return _prog(fn, jnp.zeros(16, jnp.float64))


def test_collectives_flag_uncontracted_and_drift(psum_prog):
    f = _audit(psum_prog, {}, checks=["collective-contract"])
    assert _ids(f) == ["collective-contract"]
    assert "uncontracted" in f[0].message

    good = {"collectives": {"all_reduce": {"count": 1, "max_elems": 2}}}
    assert _audit(psum_prog, good, checks=["collective-contract"]) == []

    drift = {"collectives": {"all_reduce": {"count": 3, "max_elems": 2}}}
    f = _audit(psum_prog, drift, checks=["collective-contract"])
    assert len(f) == 1 and "count drifted" in f[0].message

    bound = {"collectives": {"all_reduce": {"count": 1, "max_elems": 1}}}
    f = _audit(psum_prog, bound, checks=["collective-contract"])
    assert len(f) == 1 and "over the contract bound" in f[0].message


def test_collectives_flag_stale_contract_entry():
    prog = _prog(lambda x: x * 2.0, jnp.zeros(4, jnp.float64))
    stale = {"collectives": {"all_gather": {"count": 2}}}
    f = _audit(prog, stale, checks=["collective-contract"])
    assert len(f) == 1 and "stale contract" in f[0].message
    # bound-only entries rot silently once the op vanishes: also stale
    bound_only = {"collectives": {"all_gather": {"max_elems": 100}}}
    f = _audit(prog, bound_only, checks=["collective-contract"])
    assert len(f) == 1 and "stale contract" in f[0].message


def test_collectives_require_a_count_pin(psum_prog):
    # a contracted op present in the program must pin its static count
    bound_only = {"collectives": {"all_reduce": {"max_elems": 2}}}
    f = _audit(psum_prog, bound_only, checks=["collective-contract"])
    assert len(f) == 1 and "no `count` pin" in f[0].message


def test_collectives_suppressed_with_contract_entry(psum_prog):
    contract = {"suppress": [{
        "check": "collective-contract", "match": "uncontracted collective",
        "reason": "fixture: deliberate psum under test"}]}
    assert _audit(psum_prog, contract, checks=["collective-contract"]) == []


# --------------------------------------------------------------- dtype-flow

def _promoting(x):
    # a deliberate narrow->wide edge on the traced path
    return x.astype(jnp.float64) * 2.0


def test_dtype_flags_promotion_edge():
    prog = _prog(_promoting, jnp.zeros(4, jnp.float32))
    f = _audit(prog, {}, checks=["dtype-flow"])
    assert len(f) == 1 and "float32->float64" in f[0].message

    pinned = {"dtype": {"promotions": {"float32->float64": 1}}}
    assert _audit(prog, pinned, checks=["dtype-flow"]) == []

    drifted = {"dtype": {"promotions": {"float32->float64": 2}}}
    f = _audit(prog, drifted, checks=["dtype-flow"])
    assert len(f) == 1 and "count drifted" in f[0].message


def test_dtype_flags_stale_promotion_pin():
    prog = _prog(lambda x: x + 1.0, jnp.zeros(4, jnp.float64))
    stale = {"dtype": {"promotions": {"float32->float64": 1}}}
    f = _audit(prog, stale, checks=["dtype-flow"])
    assert len(f) == 1 and "stale contract" in f[0].message


def test_dtype_suppressed_via_contract():
    prog = _prog(_promoting, jnp.zeros(4, jnp.float32))
    contract = {"suppress": [{
        "check": "dtype-flow", "match": "float32->float64",
        "reason": "fixture: the refinement-merge pattern"}]}
    assert _audit(prog, contract, checks=["dtype-flow"]) == []


# ---------------------------------------------------------------- host-sync

def _callback_prog():
    def fn(x):
        y = jax.pure_callback(
            lambda a: np.asarray(a), jax.ShapeDtypeStruct(x.shape, x.dtype), x)
        return y + x

    return _prog(fn, jnp.zeros(3, jnp.float64))


def test_host_sync_flags_pure_callback():
    f = _audit(_callback_prog(), {}, checks=["host-sync"])
    assert len(f) == 1 and "pure_callback" in f[0].message


def test_host_sync_allowed_by_contract_and_stale_allowance():
    allowed = {"host_sync": {"allowed_callbacks": ["pure_callback"]}}
    assert _audit(_callback_prog(), allowed, checks=["host-sync"]) == []

    clean = _prog(lambda x: x * 2.0, jnp.zeros(3, jnp.float64))
    f = _audit(clean, allowed, checks=["host-sync"])
    assert len(f) == 1 and "stale contract" in f[0].message


# ----------------------------------------------------------------- donation

def test_donation_check_both_directions():
    x = jnp.zeros(8, jnp.float64)

    donating = AuditProgram(
        name="synthetic", layer="test", summary="",
        build=lambda: built_from(jax.jit(lambda v: v + 1.0,
                                         donate_argnums=(0,)), x))
    plain = _prog(lambda v: v + 1.0, x)

    assert _audit(donating, {"donation": {"donated": True}},
                  checks=["donation"]) == []
    f = _audit(donating, {"donation": {"donated": False}},
               checks=["donation"])
    assert len(f) == 1 and "rollback" in f[0].message

    assert _audit(plain, {"donation": {"donated": False}},
                  checks=["donation"]) == []
    f = _audit(plain, {"donation": {"donated": True}}, checks=["donation"])
    assert len(f) == 1 and "no aliasing marker" in f[0].message


# ----------------------------------------------------------- retrace-budget

def test_retrace_budget_flags_over_budget_and_missing_probe():
    x = jnp.zeros(2, jnp.float64)
    over = _prog(lambda v: v, x, probe=lambda: 3)
    f = _audit(over, {"retrace": {"max_traces": 1}},
               checks=["retrace-budget"])
    assert len(f) == 1 and "traced 3x" in f[0].message

    ok = _prog(lambda v: v, x, probe=lambda: 1)
    assert _audit(ok, {"retrace": {"max_traces": 1}},
                  checks=["retrace-budget"]) == []

    no_probe = _prog(lambda v: v, x)
    f = _audit(no_probe, {"retrace": {"max_traces": 1}},
               checks=["retrace-budget"])
    assert len(f) == 1 and "no retrace probe" in f[0].message


# ----------------------------------------------- contract file / suppression

def test_contract_validation_findings(tmp_path, monkeypatch):
    monkeypatch.setattr(engine, "CONTRACT_DIR", str(tmp_path))
    _, f = engine.load_contract("nope")
    assert len(f) == 1 and "no contract file" in f[0].message

    (tmp_path / "bad.toml").write_text(
        '[program]\nname = "other"\n[typo_section]\nx = 1\n'
        '[[suppress]]\ncheck = "dtype-flow"\nmatch = "x"\n'
        '[[suppress]]\ncheck = "dtype-flow"\nmatch = ""\nreason = "r"\n')
    _, f = engine.load_contract("bad")
    msgs = " | ".join(x.message for x in f)
    assert "unknown contract section" in msgs
    assert "copy-paste drift" in msgs
    assert "missing its reason" in msgs
    # an empty match would blanket-suppress its whole check
    assert "non-empty `match`" in msgs


def test_empty_suppress_match_never_suppresses():
    prog = _prog(_promoting, jnp.zeros(4, jnp.float32))
    blanket = {"suppress": [{"check": "dtype-flow", "match": "",
                             "reason": "illegitimate blanket"}]}
    # the finding survives (and the dead entry is itself reported unused)
    f = _audit(prog, blanket, checks=["dtype-flow"])
    assert sorted(x.check for x in f) == ["contract", "dtype-flow"]
    assert any("float32->float64" in x.message for x in f)


def test_unused_suppression_is_a_finding():
    prog = _prog(lambda x: x + 1.0, jnp.zeros(2, jnp.float64))
    contract = {"suppress": [{"check": "dtype-flow", "match": "never-hits",
                             "reason": "stale"}]}
    f = _audit(prog, contract)
    assert len(f) == 1 and "unused suppression" in f[0].message
    # a check-filtered run must not flag suppressions for skipped checks
    assert _audit(prog, contract, checks=["host-sync"]) == []


def test_dump_contract_roundtrips_through_toml():
    prog = _prog(_promoting, jnp.zeros(4, jnp.float32), name="dumpme")
    text = engine.dump_contract(prog)
    data = toml_io.loads(text)  # the quoted "float32->float64" key must parse
    assert data["program"]["name"] == "dumpme"
    assert data["dtype"]["promotions"]["float32->float64"] == 1


# ------------------------------------------------- the real program matrix

def test_gmres_program_is_contract_clean_end_to_end():
    """The solver-layer entry point through the real tree contract,
    retrace probe included (cheap: a 64x64 f32 solve)."""
    assert audit_main(["--program", "gmres_f32"]) == 0


def test_perturbed_contract_fails_the_cli(tmp_path, monkeypatch):
    """The acceptance property: perturbing a contract file flips the CLI
    to a non-zero exit."""
    real = engine.contract_path("gmres_f32")
    perturbed = toml_io.load(real)
    perturbed["collectives"] = {"all_gather": {"count": 1}}
    (tmp_path / "gmres_f32.toml").write_text(toml_io.dumps(perturbed))
    monkeypatch.setattr(engine, "CONTRACT_DIR", str(tmp_path))
    assert audit_main(["--program", "gmres_f32", "--check",
                       "collective-contract"]) == 1


def test_cli_usage_paths():
    assert audit_main(["--list-checks"]) == 0
    assert audit_main(["--list-programs"]) == 0
    assert audit_main(["--program", "bogus"]) == 2
    assert audit_main(["--check", "bogus"]) == 2


@pytest.mark.slow
def test_spmd_ladder_is_contract_clean():
    """d2/d4 lowering fixtures (d8 is pinned per-commit by test_spmd's
    wrapper): the collective inventory scales exactly as contracted —
    density-bounded all_gather at every mesh size, ppermute blocks halving
    with D. Slow: two full coupled shard_map lowerings."""
    from skellysim_tpu.audit.programs import get_program

    for name in ("step_spmd_d2", "step_spmd_d4"):
        prog = get_program(name)
        assert engine.run_program_audit(prog) == [], name


@pytest.mark.slow
def test_full_matrix_is_contract_clean():
    """`python -m skellysim_tpu.audit` over the whole registered matrix —
    the CI gate's exact invocation, exit 0 on this tree."""
    assert audit_main([]) == 0
