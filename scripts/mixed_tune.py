"""Sweep the mixed solver's inner tolerance on the live backend.

The mixed-precision walkthrough solve (`solver.gmres_ir`) trades refinement
sweeps against inner iterations: each sweep costs one HIGH-precision
residual matvec (double-float pairwise tiles + emulated-f64 dense ops —
tens of times an f32 inner iteration at scale), while a tighter
``inner_tol`` costs extra f32 Krylov iterations. The r3 default (1e-4) was
chosen by total-inner-iteration count; at shell-6000 scale the hi matvec
dominates, so fewer sweeps may win. This script measures the actual wall
per solve across an inner_tol ladder at a given scene scale.

Usage:
    python scripts/mixed_tune.py [--shell-n 6000] [--tols 1e-3,1e-4,1e-5,3e-6]
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--shell-n", type=int, default=6000)
    ap.add_argument("--body-n", type=int, default=400)
    ap.add_argument("--tol", type=float, default=1e-10)
    ap.add_argument("--trials", type=int, default=3)
    ap.add_argument("--tols", type=str, default="1e-3,1e-4,1e-5,3e-6")
    ap.add_argument("--refine-impls", type=str, default="",
                    help="comma list to sweep refine_pair_impl at the best "
                         "inner_tol, e.g. 'df,pallas_df,exact'")
    args = ap.parse_args()
    from skellysim_tpu.params import REFINE_PAIR_IMPLS

    impls = [s for s in args.refine_impls.split(",") if s]
    bad = set(impls) - set(REFINE_PAIR_IMPLS)
    if bad:
        # dataclasses.replace skips System.__init__'s validation; a typo'd
        # name would silently bench the exact tile under the wrong label —
        # and must fail HERE, not after the minutes-long inner_tol sweep
        raise SystemExit(f"unknown refine impls: {sorted(bad)}")

    import jax

    jax.config.update("jax_enable_x64", True)
    try:
        here = os.path.dirname(os.path.abspath(__file__))
        jax.config.update("jax_compilation_cache_dir",
                          os.path.join(here, "..", ".jax_cache"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass
    import jax.numpy as jnp
    import numpy as np

    import bench

    t0 = time.perf_counter()
    system, state = bench._walkthrough_state(args.shell_n, args.body_n,
                                             jnp.float64, args.tol, True)
    print(json.dumps({"backend": jax.default_backend(),
                      "shell_n": args.shell_n,
                      "setup_s": round(time.perf_counter() - t0, 1)}),
          flush=True)

    best = (None, float("inf"))
    for tol_s in args.tols.split(","):
        inner = float(tol_s)
        system.params = dataclasses.replace(system.params, inner_tol=inner)
        # params live on `self`, not in the jit signature: rebuild the jit
        # wrapper so the new inner_tol is baked into a fresh program
        out = bench._solve_rate(system, state, trials=args.trials)
        print(json.dumps({"inner_tol": inner, **out}), flush=True)
        if out["residual_true"] <= args.tol and out["wall_s"] < best[1]:
            best = (inner, out["wall_s"])

    if impls and best[0] is None:
        # no swept inner_tol validated against --tol: benching impls at an
        # arbitrary tolerance would misread as a validated winner
        print(json.dumps({"refine_impl_sweep": "skipped",
                          "reason": f"no inner_tol reached {args.tol}"}),
              flush=True)
        impls = []
    for impl in impls:
        system.params = dataclasses.replace(
            system.params, inner_tol=best[0], refine_pair_impl=impl)
        out = bench._solve_rate(system, state, trials=args.trials)
        print(json.dumps({"refine_pair_impl": impl,
                          "inner_tol": best[0], **out}), flush=True)


if __name__ == "__main__":
    main()
