"""Diagnose the oocyte/ellipsoid GMRES iteration counts (VERDICT r4 #5).

Rebuilds the bench's BASELINE #5 scene (surface-of-revolution shell +
clamped fibers) and prints per-restart-cycle implicit/explicit residuals
for solver variants, so the preconditioner/restart interplay is visible.
"""

import sys
import time

sys.path.insert(0, "/root/repo")

from skellysim_tpu.utils.bootstrap import force_cpu_devices

force_cpu_devices(1)

import jax

jax.config.update("jax_enable_x64", True)

import numpy as np
import jax.numpy as jnp

import bench
from skellysim_tpu.fibers import container as fc
from skellysim_tpu.params import Params
from skellysim_tpu.periphery import periphery as peri
from skellysim_tpu.periphery import shapes
from skellysim_tpu.system import System
from skellysim_tpu.solver import gmres


def build_scene(kind="revolution", n_fibers=16, fiber_nodes=32, shell_n=192,
                dtype=jnp.float64):
    if kind == "ellipsoid":
        a, b, c = 7.8, 6.0, 6.0
        spec = shapes.ellipsoid_shape(shell_n, a, b, c)
        p = 1.6075
        area = 4 * np.pi * (((a*b)**p + (a*c)**p + (b*c)**p) / 3) ** (1/p)
        shape = peri.PeripheryShape(kind="ellipsoid", abc=(a, b, c))
    else:
        env = {"n_nodes_target": shell_n, "lower_bound": -3.75,
               "upper_bound": 3.75, "T": 0.72, "p1": 0.4, "p2": 0.2,
               "length": 7.5,
               "height": "0.5 * T * ((1 + 2*x/length)**p1) "
                         "* ((1 - 2*x/length)**p2) * length"}
        spec = shapes.surface_of_revolution_shape(env)
        area = 4 * np.pi * 2.0 ** 2
        shape = peri.PeripheryShape(kind="generic")
    N = len(spec.nodes)
    normals = -spec.node_normals
    weights = np.full(N, area / N)
    op, M_inv = bench._device_shell_operator(spec.nodes, normals, weights,
                                             dtype, precond_dtype=jnp.float32)
    shell = peri.make_state(spec.nodes, normals, weights, op, M_inv,
                            dtype=dtype, precond_dtype=jnp.float32)
    x, nf = bench._clamped_fiber_field(spec, n_fibers, fiber_nodes, 1.0, dtype)
    fibers = fc.make_group(x, lengths=1.0, bending_rigidity=2.5e-3,
                           radius=0.0125, force_scale=-0.05,
                           minus_clamped=True, dtype=dtype)
    params = Params(eta=1.0, dt_initial=8e-3, t_final=1.0, gmres_tol=1e-10,
                    gmres_restart=60, gmres_maxiter=300,
                    adaptive_timestep_flag=False)
    system = System(params, shell_shape=shape)
    state = system.make_state(fibers=fibers, shell=shell)
    return system, state


def run_debug(system, state, restart, label):
    p = system.params
    state2, caches, body_caches, shell_rhs, body_rhs = system._prep(state)
    rhs_parts = [c.RHS.reshape(-1) for c in (caches or [])]
    if shell_rhs is not None:
        rhs_parts.append(shell_rhs)
    rhs = jnp.concatenate(rhs_parts)
    mv = lambda v: system._apply_matvec(state2, caches, body_caches, v)
    pc = lambda v: system._apply_precond(state2, caches, body_caches, v)
    t0 = time.perf_counter()
    res = gmres(mv, rhs, precond=pc, tol=p.gmres_tol, restart=restart,
                maxiter=300, debug=True)
    iters = int(res.iters)
    wall = time.perf_counter() - t0
    print(f"[{label}] iters={iters} converged={bool(res.converged)} "
          f"implicit={float(res.residual):.3e} true={float(res.residual_true):.3e} "
          f"wall={wall:.1f}s", flush=True)
    return res


def run_gs(system, state, restart, label, order="shell_first", sweeps=1):
    """GMRES with a block GAUSS-SEIDEL preconditioner: the block-Jacobi
    solves plus the fiber<->shell coupling applied triangularly. The
    coupling term A_fs y_s (or A_sf y_f) is extracted from the full
    matvec at (0, y_s) — wasteful (computes all rows) but exact for the
    experiment."""
    p = system.params
    state2, caches, body_caches, shell_rhs, body_rhs = system._prep(state)
    rhs_parts = [c.RHS.reshape(-1) for c in (caches or [])]
    if shell_rhs is not None:
        rhs_parts.append(shell_rhs)
    rhs = jnp.concatenate(rhs_parts)
    fib_size, shell_size, body_size = system._sizes(state2)
    mv = lambda v: system._apply_matvec(state2, caches, body_caches, v)
    pc_jac = lambda v: system._apply_precond(state2, caches, body_caches, v)

    def pc_gs(x):
        x_f = x[:fib_size]
        x_s = x[fib_size:fib_size + shell_size]
        zf = jnp.zeros(fib_size, dtype=x.dtype)
        zs = jnp.zeros(shell_size, dtype=x.dtype)
        if order == "shell_first":
            y_s = pc_jac(jnp.concatenate([zf, x_s]))[fib_size:]
            a = mv(jnp.concatenate([zf, y_s]))  # coupling rows
            x_f2 = x_f - a[:fib_size]
            y_f = pc_jac(jnp.concatenate([x_f2, zs]))[:fib_size]
            return jnp.concatenate([y_f, y_s])
        else:  # fiber_first
            y_f = pc_jac(jnp.concatenate([x_f, zs]))[:fib_size]
            a = mv(jnp.concatenate([y_f, zs]))
            x_s2 = x_s - a[fib_size:]
            y_s = pc_jac(jnp.concatenate([zf, x_s2]))[fib_size:]
            return jnp.concatenate([y_f, y_s])

    def pc_sym(x):
        # symmetric sweep: shell-first then fiber-first correction on shell
        x_f = x[:fib_size]
        x_s = x[fib_size:fib_size + shell_size]
        zf = jnp.zeros(fib_size, dtype=x.dtype)
        y_s = pc_jac(jnp.concatenate([zf, x_s]))[fib_size:]
        a = mv(jnp.concatenate([zf, y_s]))
        x_f2 = x_f - a[:fib_size]
        y_f = pc_jac(jnp.concatenate([x_f2, jnp.zeros(shell_size, x.dtype)]))[:fib_size]
        a2 = mv(jnp.concatenate([y_f, jnp.zeros(shell_size, x.dtype)]))
        x_s2 = x_s - a2[fib_size:]
        y_s2 = pc_jac(jnp.concatenate([zf, x_s2]))[fib_size:]
        return jnp.concatenate([y_f, y_s2])

    pc = pc_sym if order == "sym" else pc_gs
    t0 = time.perf_counter()
    res = gmres(mv, rhs, precond=pc, tol=p.gmres_tol, restart=restart,
                maxiter=300, debug=True)
    iters = int(res.iters)
    wall = time.perf_counter() - t0
    print(f"[{label}] iters={iters} converged={bool(res.converged)} "
          f"implicit={float(res.residual):.3e} true={float(res.residual_true):.3e} "
          f"wall={wall:.1f}s", flush=True)
    return res


if __name__ == "__main__":
    kind = sys.argv[1] if len(sys.argv) > 1 else "revolution"
    mode = sys.argv[2] if len(sys.argv) > 2 else "all"
    system, state = build_scene(kind)
    if mode in ("all", "jacobi"):
        print(f"=== {kind}: baseline block-Jacobi restart=60 ===", flush=True)
        run_debug(system, state, 60, "jacobi")
    if mode in ("all", "gs"):
        print(f"=== {kind}: Gauss-Seidel shell-first ===", flush=True)
        run_gs(system, state, 60, "gs-shell-first", order="shell_first")
        print(f"=== {kind}: Gauss-Seidel fiber-first ===", flush=True)
        run_gs(system, state, 60, "gs-fiber-first", order="fiber_first")
        print(f"=== {kind}: symmetric sweep ===", flush=True)
        run_gs(system, state, 60, "gs-sym", order="sym")
