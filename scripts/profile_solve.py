"""Profile the walkthrough-scale coupled solve on the live backend.

Round-5 question: the mixed-precision solve at the reference walkthrough
scale (1 fiber + 400-node body + spherical shell) measures ~0.5 s/solve on
one TPU chip against the reference's 0.328 s on a workstation — at this
size the kernels are microseconds, so the wall is overheads (while_loop
step latency, refinement sweeps, small-op dispatch). This script reports
`bench._bench_coupled` (the exact measurement boundary behind the 0.328 s
comparison, vs_ref included) and optionally captures an XLA profiler trace
of one steady-state solve for the op-level attribution.

Usage:
    python scripts/profile_solve.py [--shell-n 2000] [--trace /tmp/xprof]

Open the trace with TensorBoard (`tensorboard --logdir /tmp/xprof`) or
xprof.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--shell-n", type=int, default=2000)
    ap.add_argument("--body-n", type=int, default=400)
    ap.add_argument("--tol", type=float, default=1e-10)
    ap.add_argument("--trials", type=int, default=5)
    ap.add_argument("--trace", type=str, default=None,
                    help="directory for a jax.profiler trace (optional)")
    ap.add_argument("--kernel-impl", type=str, default="exact")
    args = ap.parse_args()

    import jax

    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp
    import numpy as np

    import bench

    out, system, state = bench._bench_coupled(
        args.shell_n, args.body_n, jnp.float64, args.tol,
        trials=max(args.trials, 1), mixed=True,
        kernel_impl=args.kernel_impl, return_scene=True)

    if args.trace:
        # reuse the scene _bench_coupled built; warm OUTSIDE the trace so
        # the capture holds one steady-state solve, not tracing/compilation
        step = jax.jit(system._solve_impl)
        np.asarray(step(state)[1])  # warm + drain (compile is process-cached)
        with jax.profiler.trace(args.trace):
            np.asarray(step(state)[1])

    print(json.dumps({
        "backend": jax.default_backend(),
        "kernel_impl": args.kernel_impl,
        **out,
        "trace_dir": args.trace,
    }))


if __name__ == "__main__":
    main()
