"""Profile the walkthrough-scale coupled solve on the live backend.

Round-5 question: the mixed-precision solve at the reference walkthrough
scale (1 fiber + 400-node body + spherical shell) measures ~0.5 s/solve on
one TPU chip against the reference's 0.328 s on a workstation — at this
size the kernels are microseconds, so the wall is overheads (while_loop
step latency, refinement sweeps, small-op dispatch). This script reports
the bench-comparable wall (`bench._solve_rate`, the same measurement
boundary as the 0.328 s comparison) and optionally captures an XLA
profiler trace of one solve for the op-level attribution.

Usage:
    python scripts/profile_solve.py [--shell-n 2000] [--trace /tmp/xprof]

Open the trace with TensorBoard (`tensorboard --logdir /tmp/xprof`) or
xprof.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--shell-n", type=int, default=2000)
    ap.add_argument("--body-n", type=int, default=400)
    ap.add_argument("--tol", type=float, default=1e-10)
    ap.add_argument("--trials", type=int, default=5)
    ap.add_argument("--trace", type=str, default=None,
                    help="directory for a jax.profiler trace (optional)")
    ap.add_argument("--kernel-impl", type=str, default="exact")
    args = ap.parse_args()

    import jax

    jax.config.update("jax_enable_x64", True)
    import numpy as np

    import bench

    t0 = time.perf_counter()
    system, state = bench._walkthrough_state(
        args.shell_n, args.body_n, jax.numpy.float64, args.tol, mixed=True,
        kernel_impl=args.kernel_impl)
    setup_s = time.perf_counter() - t0

    # same measurement boundary as the bench's 0.328 s comparison
    t0 = time.perf_counter()
    out = bench._solve_rate(system, state, trials=max(args.trials, 1))
    total_s = time.perf_counter() - t0
    compile_s = total_s - out["wall_s"] * max(args.trials, 1)

    if args.trace:
        step = jax.jit(system._solve_impl)
        with jax.profiler.trace(args.trace):
            _, sol, _ = step(state)
            np.asarray(sol)

    print(json.dumps({
        "backend": jax.default_backend(),
        "kernel_impl": args.kernel_impl,
        "shell_n": args.shell_n,
        "setup_s": round(setup_s, 2),
        "compile_s": round(compile_s, 2),
        **out,
        "trace_dir": args.trace,
    }))


if __name__ == "__main__":
    main()
