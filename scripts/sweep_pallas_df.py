"""Sweep Pallas DF tile shapes on the live backend.

The exact Pallas tiles were swept in round 5 ((256, 1024) stokeslet /
(128, 2048) stresslet on v5e); the DF tiles hold ~3x the live temporaries,
so their VMEM-feasible frontier is different. This sweeps (tile_t, tile_s)
for both DF kernels, printing rate + accuracy per shape — run it on the
TPU and pin the winners as `ops.pallas_df.DF_TILE_T/S`.

Usage: python scripts/sweep_pallas_df.py [--n 16384] [--trials 2]
"""

from __future__ import annotations

import argparse
import itertools
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

TILES_T = (64, 128, 256)
TILES_S = (128, 256, 512, 1024)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=16384)
    ap.add_argument("--trials", type=int, default=2)
    ap.add_argument("--kernel", choices=("stokeslet", "stresslet", "both"),
                    default="both")
    ap.add_argument("--interpret", action="store_true",
                    help="CPU smoke mode: force the CPU backend (unregisters "
                         "the axon plugin, which can block when the tunnel "
                         "is wedged) and run the tiles in interpret mode")
    args = ap.parse_args()

    if args.interpret:
        from skellysim_tpu.utils.bootstrap import force_cpu_devices

        force_cpu_devices()
        # interpret mode evaluates grid cells at Python speed: the TPU
        # default (16384) would run for hours; clamp to smoke scale
        args.n = min(args.n, 512)
    import jax

    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp
    import numpy as np

    from skellysim_tpu.ops import kernels
    from skellysim_tpu.ops.pallas_df import (stokeslet_pallas_df,
                                             stresslet_pallas_df)

    n = args.n
    rng = np.random.default_rng(1)
    r = jnp.asarray(rng.uniform(-5, 5, (n, 3)), dtype=jnp.float64)
    f = jnp.asarray(rng.standard_normal((n, 3)), dtype=jnp.float64)
    S = jnp.asarray(rng.standard_normal((n, 3, 3)), dtype=jnp.float64)
    print(json.dumps({"backend": jax.default_backend(), "n": n}), flush=True)

    import bench  # shared timing helper (host-fetch barrier, see bench._rate)

    # accuracy oracle on a subsample (full f64 dense is slow on TPU);
    # compute only the selected kernels' references — emulated-f64 work for
    # a deselected kernel is pure waste on the chip
    sub = np.random.default_rng(0).choice(n, size=min(n, 256), replace=False)
    cases = []
    if args.kernel in ("stokeslet", "both"):
        cases.append(("stokeslet", stokeslet_pallas_df, f,
                      np.asarray(kernels.stokeslet_direct(r, r[sub], f, 1.0))))
    if args.kernel in ("stresslet", "both"):
        cases.append(("stresslet", stresslet_pallas_df, S,
                      np.asarray(kernels.stresslet_direct(r, r[sub], S, 1.0))))

    for tt, ts in itertools.product(TILES_T, TILES_S):
        for name, fn, payload, ref in cases:
            try:
                call = lambda: fn(r, r, payload, 1.0, tile_t=tt, tile_s=ts,
                                  interpret=args.interpret)
                rr = bench._rate(call, n * n, trials=args.trials)
                err = (np.linalg.norm(np.asarray(call())[sub] - ref)
                       / np.linalg.norm(ref))
                print(json.dumps({"kernel": name, "tile": [tt, ts],
                                  "gpairs_per_s": round(rr / 1e9, 3),
                                  "rel_err": float(err)}), flush=True)
            except Exception as e:
                print(json.dumps({"kernel": name, "tile": [tt, ts],
                                  "error": repr(e).splitlines()[0][:160]}),
                      flush=True)


if __name__ == "__main__":
    main()
