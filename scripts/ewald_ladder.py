"""Ewald-vs-dense crossover ladder (VERDICT r4 #2).

Measures dense O(N^2) Stokeslet matvec wall vs the spectral-Ewald
evaluator at a ladder of node counts, constant source density — the
measured crossover table. Run with a clean env so the axon sitecustomize
cannot block CPU runs:

    env -i PATH=... HOME=/root JAX_PLATFORMS=cpu python scripts/ewald_ladder.py
"""

import sys
import time

sys.path.insert(0, "/root/repo")

import numpy as np
import jax
import jax.numpy as jnp

jax.config.update("jax_enable_x64", True)

from skellysim_tpu.ops import ewald as ew
from skellysim_tpu.ops import kernels


def main(sizes=(6400, 16000, 40000, 100000, 200000)):
    dtype = jnp.float32
    rng = np.random.default_rng(100)
    rows = []
    for n in sizes:
        print(f"--- n={n}", flush=True)
        n_fibers = -(-n // 64)
        box = 20.0 * (n / 640000.0) ** (1.0 / 3.0)
        origins = rng.uniform(-box / 2, box / 2, (n_fibers, 3))
        dirs = rng.normal(size=(n_fibers, 3))
        dirs /= np.linalg.norm(dirs, axis=1, keepdims=True)
        t = np.linspace(0, 1.0, 64)
        r = (origins[:, None, :]
             + t[None, :, None] * dirs[:, None, :]).reshape(-1, 3)[:n]
        r = jnp.asarray(r, dtype=dtype)
        f = jnp.asarray(rng.standard_normal((n, 3)), dtype=dtype)
        if n <= 40000:
            np.asarray(kernels.stokeslet_direct(r, r, f, 1.0, impl="mxu"))
            t0 = time.perf_counter()
            np.asarray(kernels.stokeslet_direct(r, r, f, 1.0, impl="mxu"))
            dense_wall = time.perf_counter() - t0
        else:
            dense_wall = None
        t0 = time.perf_counter()
        plan = ew.plan_ewald(np.asarray(r), eta=1.0, tol=1e-4)
        print(f"plan done M={plan.M} near={plan.near_mode} K={plan.K}",
              flush=True)
        np.asarray(ew.stokeslet_ewald(plan, r, r, f))
        t_first = time.perf_counter() - t0
        t0 = time.perf_counter()
        uE = np.asarray(ew.stokeslet_ewald(plan, r, r, f))
        t_steady = time.perf_counter() - t0
        sub = np.random.default_rng(0).choice(n, size=min(n, 256),
                                              replace=False)
        uD = np.asarray(kernels.stokeslet_direct(r, r[sub], f, 1.0))
        err = (np.linalg.norm(uE[sub] - uD)
               / max(np.linalg.norm(uD), 1e-300))
        sp = (dense_wall / t_steady) if dense_wall else None
        rows.append((n, dense_wall, t_steady, t_first, sp, err))
        print(f"n={n}: dense={dense_wall} ewald={t_steady:.3f} "
              f"first={t_first:.1f} speedup={sp} err={err:.2e}", flush=True)
    return rows


if __name__ == "__main__":
    sizes = ([int(s) for s in sys.argv[1:]]
             if len(sys.argv) > 1 else (6400, 16000, 40000, 100000, 200000))
    main(sizes)
