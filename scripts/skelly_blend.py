"""Blender rendering script for skellysim_tpu trajectories.

Counterpart of the reference's `scripts/skelly_blend.py` (rendering toolkit,
SURVEY.md §2.2 P12): run inside Blender's Python
(`blender --python scripts/skelly_blend.py -- --traj skelly_sim.out`), it
builds animated curve objects for fibers, UV spheres for rigid bodies, and a
transparent shell for a spherical periphery, with one keyframe per trajectory
frame. Only needs msgpack/toml (auto-installed into Blender's Python on first
run, like the reference script does).
"""

import argparse
import os
import site
import subprocess
import sys

try:
    import bpy
except ImportError:
    sys.exit("run inside Blender: blender --python scripts/skelly_blend.py "
             "-- --traj skelly_sim.out")

site_dir = site.getusersitepackages()
if site_dir not in sys.path:
    sys.path.append(site_dir)

try:
    import msgpack
    import toml
except ImportError:
    PYTHON = sys.executable
    subprocess.call([PYTHON, "-m", "ensurepip"])
    subprocess.call([PYTHON, "-m", "pip", "install", "--user", "msgpack", "toml"])
    import msgpack
    import toml


def read_frames(path):
    """All trajectory frames (skips the header)."""
    frames = []
    with open(path, "rb") as fh:
        unpacker = msgpack.Unpacker(fh, raw=False)
        for obj in unpacker:
            if isinstance(obj, dict) and "time" in obj:
                frames.append(obj)
    return frames


def eigen_points(field):
    rows, cols = field[1], field[2]
    flat = field[3:]
    n = cols if rows == 3 else len(flat) // 3
    return [flat[3 * i:3 * i + 3] for i in range(n)]


def make_material(name, rgba, alpha=1.0):
    mat = bpy.data.materials.get(name) or bpy.data.materials.new(name)
    mat.use_nodes = True
    bsdf = mat.node_tree.nodes["Principled BSDF"]
    bsdf.inputs["Base Color"].default_value = rgba
    bsdf.inputs["Alpha"].default_value = alpha
    mat.blend_method = "BLEND" if alpha < 1.0 else "OPAQUE"
    return mat


def add_fiber_curve(name, points, radius, mat):
    curve = bpy.data.curves.new(name, type="CURVE")
    curve.dimensions = "3D"
    curve.bevel_depth = radius
    spline = curve.splines.new("POLY")
    spline.points.add(len(points) - 1)
    for p, xyz in zip(spline.points, points):
        p.co = (*xyz, 1.0)
    obj = bpy.data.objects.new(name, curve)
    obj.data.materials.append(mat)
    bpy.context.collection.objects.link(obj)
    return obj


def add_sphere(name, center, radius, mat, segments=32):
    bpy.ops.mesh.primitive_uv_sphere_add(radius=radius, location=center,
                                         segments=segments)
    obj = bpy.context.active_object
    obj.name = name
    obj.data.materials.append(mat)
    bpy.ops.object.shade_smooth()
    return obj


def animate(frames, config, fiber_radius_scale):
    fiber_mat = make_material("skelly_fiber", (0.8, 0.2, 0.2, 1.0))
    body_mat = make_material("skelly_body", (0.2, 0.4, 0.8, 1.0))
    shell_mat = make_material("skelly_shell", (0.9, 0.9, 0.9, 1.0), alpha=0.15)

    periphery = config.get("periphery")
    if periphery and periphery.get("shape", "sphere") == "sphere":
        add_sphere("periphery", (0, 0, 0), periphery.get("radius", 1.0),
                   shell_mat, segments=64)

    body_cfgs = config.get("bodies", [])
    first = frames[0]
    fiber_objs, body_objs = [], []
    for i, fib in enumerate(first["fibers"][1]):
        pts = eigen_points(fib["x_"])
        radius = fiber_radius_scale * fib.get("radius_", 0.0125)
        fiber_objs.append(add_fiber_curve(f"fiber_{i}", pts, radius, fiber_mat))
    bodies0 = [b for sub in first["bodies"] for b in sub]
    for i, body in enumerate(bodies0):
        radius = body_cfgs[i]["radius"] if i < len(body_cfgs) else body.get("radius_", 0.5)
        body_objs.append(add_sphere(f"body_{i}", body["position_"][3:6],
                                    radius, body_mat))

    scene = bpy.context.scene
    scene.frame_start = 1
    scene.frame_end = len(frames)
    for f_idx, frame in enumerate(frames, start=1):
        scene.frame_set(f_idx)
        for i, fib in enumerate(frame["fibers"][1]):
            if i >= len(fiber_objs):
                break
            pts = eigen_points(fib["x_"])
            spline = fiber_objs[i].data.splines[0]
            for p, xyz in zip(spline.points, pts):
                p.co = (*xyz, 1.0)
                p.keyframe_insert("co", frame=f_idx)
        bodies = [b for sub in frame["bodies"] for b in sub]
        for i, body in enumerate(bodies):
            if i >= len(body_objs):
                break
            body_objs[i].location = body["position_"][3:6]
            body_objs[i].keyframe_insert("location", frame=f_idx)


def main():
    argv = sys.argv[sys.argv.index("--") + 1:] if "--" in sys.argv else []
    ap = argparse.ArgumentParser()
    ap.add_argument("--traj", default="skelly_sim.out")
    ap.add_argument("--config", default="skelly_config.toml")
    ap.add_argument("--fiber-radius-scale", type=float, default=1.0)
    args = ap.parse_args(argv)

    frames = read_frames(args.traj)
    if not frames:
        sys.exit(f"no frames in {args.traj}")
    config = toml.load(args.config) if os.path.exists(args.config) else {}
    animate(frames, config, args.fiber_radius_scale)
    print(f"Built {len(frames)} animation frames from {args.traj}")


main()
