#!/bin/bash
# Probe the axon TPU tunnel every ~8 min; on success write a marker file.
# A real probe = device enumeration AND a small compiled matmul fetched to
# host (the tunnel can enumerate while the remote AOT compiler is wedged).
# Run in background: bash scripts/tpu_probe_loop.sh /tmp/tpu_up.marker
MARKER="${1:-/tmp/tpu_up.marker}"
LOG="${2:-/tmp/tpu_probe.log}"
while true; do
  ts=$(date -u +%FT%TZ)
  raw=$(timeout -k 10 300 python -c "
import jax, numpy as np, jax.numpy as jnp
d = jax.devices()
y = np.asarray(jnp.ones((128,128)) @ jnp.ones((128,128)))
print('PROBE_OK', d[0].platform, len(d), float(y[0,0]))
" 2>/dev/null)
  rc=$?   # timeout/python status (124 = compile hang), not grep's
  out=$(echo "$raw" | grep PROBE_OK)
  echo "$ts rc=$rc out=$out" >> "$LOG"
  if [ -n "$out" ]; then
    echo "$ts $out" > "$MARKER"
    echo "$ts TPU UP (matmul verified)" >> "$LOG"
    exit 0
  fi
  sleep 480
done
