#!/usr/bin/env python3
"""Ewald crossover tuning harness (run on the real TPU when reachable).

Scans plan knobs (target_occ, max_grid) against dense at a ladder of node
counts and prints one JSON line per measurement — the data behind the
near/far balance defaults in `ops.ewald.plan_ewald` and the
`ewald_crossover` section of bench.py. Usage:

    python scripts/tune_ewald.py [--sizes 40000,160000,640000] \
        [--occ 16,32,64] [--grids 256,384,448] [--tol 1e-4]

Each measurement times to a host fetch (block_until_ready undermeasures on
the axon tunnel) and reports rel err vs dense on a 512-target subsample.
"""

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--sizes", default="40000,160000,640000")
    ap.add_argument("--occ", default="16,32,64")
    ap.add_argument("--grids", default="448")
    ap.add_argument("--tol", type=float, default=1e-4)
    ap.add_argument("--trials", type=int, default=2)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    try:
        cache = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), ".jax_cache")
        jax.config.update("jax_compilation_cache_dir", cache)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass

    from skellysim_tpu.ops import ewald as ew
    from skellysim_tpu.ops import kernels

    print(json.dumps({"backend": jax.default_backend(),
                      "device": str(jax.devices()[0])}), flush=True)

    rng = np.random.default_rng(100)
    for n in [int(s) for s in args.sizes.split(",")]:
        n_fibers = max(1, n // 64)
        box = 20.0 * (n / 640000.0) ** (1 / 3)
        origins = rng.uniform(-box / 2, box / 2, (n_fibers, 3))
        dirs = rng.normal(size=(n_fibers, 3))
        dirs /= np.linalg.norm(dirs, axis=1, keepdims=True)
        t = np.linspace(0, 1.0, 64)
        r = (origins[:, None, :] + t[None, :, None]
             * dirs[:, None, :]).reshape(-1, 3)[:n]
        rj = jnp.asarray(r, dtype=jnp.float32)
        f = jnp.asarray(rng.standard_normal((n, 3)), dtype=jnp.float32)

        np.asarray(kernels.stokeslet_direct(rj, rj, f, 1.0, impl="mxu"))
        t0 = time.perf_counter()
        for _ in range(args.trials):
            out = kernels.stokeslet_direct(rj, rj, f, 1.0, impl="mxu")
        np.asarray(out)
        dense_wall = (time.perf_counter() - t0) / args.trials
        sub = np.random.default_rng(0).choice(n, size=min(n, 512),
                                              replace=False)
        uD = np.asarray(kernels.stokeslet_direct(rj, rj[sub], f, 1.0))
        print(json.dumps({"n": n, "dense_wall_s": round(dense_wall, 4)}),
              flush=True)

        for occ in [float(s) for s in args.occ.split(",")]:
            for grid in [int(s) for s in args.grids.split(",")]:
                try:
                    t0 = time.perf_counter()
                    plan = ew.plan_ewald(r, eta=1.0, tol=args.tol,
                                         max_grid=grid, target_occ=occ)
                    np.asarray(ew.stokeslet_ewald(plan, rj, rj, f))
                    first = time.perf_counter() - t0
                    t0 = time.perf_counter()
                    for _ in range(args.trials):
                        uE = ew.stokeslet_ewald(plan, rj, rj, f)
                    uE = np.asarray(uE)
                    wall = (time.perf_counter() - t0) / args.trials
                    err = (np.linalg.norm(uE[sub] - uD)
                           / max(np.linalg.norm(uD), 1e-300))
                    print(json.dumps({
                        "n": n, "occ": occ, "grid": grid,
                        "wall_s": round(wall, 4), "first_s": round(first, 1),
                        "speedup": round(dense_wall / max(wall, 1e-9), 2),
                        "rel_err": float(err), "M": plan.M,
                        "near_mode": plan.near_mode, "K": plan.K,
                        "max_occ": plan.max_occ}), flush=True)
                except Exception as e:
                    print(json.dumps({"n": n, "occ": occ, "grid": grid,
                                      "error": repr(e)[:160]}), flush=True)


if __name__ == "__main__":
    main()
