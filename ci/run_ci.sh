#!/bin/bash
# CI gate. The reference gates every change with ctest + pytest inside a
# GPU docker image (`/root/reference/ci/Jenkinsfile:1-37`, `ci/Dockerfile`);
# this script is the equivalent in-repo entry point (VERDICT r4 #3).
#
# Usage: ci/run_ci.sh [fast|full|nightly]
#   fast    — per-commit gate: byte-compile lint + the skelly-lint static
#             analysis gate (dtype/trace/sharding discipline, docs/lint.md)
#             + the non-slow, non-tpu suite on the 8-device virtual CPU
#             mesh (~17 min measured on the 1-core build box; integration
#             tests > 45 s are slow-marked to keep this tier
#             per-commit-sized)
#   full    — pre-merge: everything but tpu-marked tests (~35 min on the
#             1-core box)
#   nightly — full suite including @pytest.mark.tpu (needs the tunnel up)
set -euo pipefail
cd "$(dirname "$0")/.."
TIER="${1:-fast}"

echo "== lint: byte-compile every source file =="
python -m compileall -q skellysim_tpu tests scripts ci bench.py __graft_entry__.py

echo "== lint: skelly-lint static analysis (dtype/trace/sharding) =="
# gating in EVERY tier: a dtype leak or host sync on the hot path is exactly
# the class of defect value-checking tests miss (commit 46b498b; docs/lint.md)
JAX_PLATFORMS=cpu python -m skellysim_tpu.lint skellysim_tpu/

echo "== audit: skelly-fence Pallas DMA-race/VMEM verifier (docs/audit.md) =="
# kernel-level static verification, in EVERY tier: the fused ring kernels
# (which CPU CI can never execute — that is the point) and the gridded
# tile kernels are traced and proven against their [dma] contracts:
# read-before-arrival ordering, overwrite-in-flight (the ENTRY+EXIT
# barrier protocol model-checked, phase skew bound pinned), semaphore
# credit balance, and the VMEM footprint from the SAME formula
# `fused_ring_fits` consults at build time. Zero suppressions. The full
# audit below re-covers this; the explicit gate keeps the kernel exit
# code visible on its own. Measured ~1.5 s total on the CI box — noise
# against the fast tier's 780 s budget guard.
JAX_PLATFORMS=cpu python -m skellysim_tpu.audit --check dma

echo "== audit: skelly-maskflow padded-lane non-interference (docs/audit.md) =="
# taint analysis over BOTH matrices (programs and Pallas kernels), in
# EVERY tier: every padded capacity axis declared in [[mask.axes]] is
# statically proven unable to contaminate live physics — no pad-escape,
# no 0*inf multiplicative masking, no unmasked reductions or
# unsentineled argreduces — and every output's pad class (pad-exact-zero
# / pad-passthrough / live-only) matches its [mask.outputs] pin. Zero
# suppressions except di_device's two documented config_rank
# rank-ledger reads. The full audit below re-covers this; the explicit
# gate keeps the masking exit code visible on its own. Measured ~25 s
# for the 16-entry matrix (<2 s per program; dominated by tracing, not
# analysis) — noise against the fast tier's 780 s budget guard.
python -m skellysim_tpu.audit --check mask

echo "== audit: skelly-audit lowered-program contracts (docs/audit.md) =="
# the compiled-program twin of the lint gate, in EVERY tier: every
# registered entry point (single-chip step, step_spmd on 2/4/8-device
# meshes, ensemble vmap step, bare GMRES) is traced + lowered and checked
# against audit/contracts/*.toml — collective inventory (incl. the
# density-bounded all-gather), dtype promotion edges, host callbacks,
# donation markers, retrace budgets, AND the skelly-rep replication-flow
# analysis (`--check replication`, docs/parallel.md "Replication
# discipline"): the d2/d4/d8 mesh programs must statically PROVE they
# cannot deadlock (no varying while/cond predicates, no collectives under
# divergence, replicated outputs verified) with zero suppressions, plus
# the skelly-fence `dma` check over the Pallas kernel registry and the
# skelly-maskflow `mask` check gated above. Fails
# on any unsuppressed finding or unused suppression. (Bootstraps its own
# 8-device CPU + x64 backend.)
python -m skellysim_tpu.audit

echo "== obs: skelly-scope cost baselines (docs/observability.md) =="
# the runtime twin of the audit gate, in EVERY tier: every registered
# program is compiled and XLA's static cost/memory analyses are checked
# against obs/baselines/*.toml — uncovered programs, stale baselines, and
# >tol_pct drift (regression OR improvement) all fail. Deliberate changes
# re-baseline via `obs cost --update`. (~35 s with a warm .jax_cache —
# the compile cache is shared with bench.py; cold runs pay ~40 s more.)
python -m skellysim_tpu.obs cost --check

echo "== obs: skelly-pulse bench-history regression gate =="
# skelly-pulse: diff the archived bench rounds (benchmarks/MULTICHIP_r*)
# on their gated ladder metrics — a coupled-solve speedup regression
# beyond 25% on non-downscaled rounds fails CI here instead of waiting
# for someone to eyeball two JSONs (downscaled CPU rounds warn only;
# skelly-roofline adds the vs-BEST-round gate, so slow multi-round drift
# that never trips an adjacent diff still fails here). Pure JSON
# parsing, <1 s.
python -m skellysim_tpu.obs perf --compare benchmarks/

echo "== obs: checked-in campaign manifest + headline tables =="
# skelly-roofline: the committed CAMPAIGN round must satisfy `obs
# campaign`'s validator (provenance keys, explicit downscale bool, gate
# verdict), and the generated headline table in docs/performance.md must
# match what --render-headlines derives from benchmarks/ (exit 1 = stale
# table, the config-reference pattern). Pure JSON parsing, <1 s.
python -m skellysim_tpu.obs campaign \
  "$(ls benchmarks/CAMPAIGN_r*.json | sort | tail -1)"
python bench.py --render-headlines --check

echo "== bench: one-group campaign smoke (skelly-roofline) =="
# exit-code-gated end-to-end: warm-cache pre-pass (one unprofiled flight
# child fills .jax_cache), then `bench.py --campaign` over just the
# flight group with every artifact path redirected — must complete on
# the CPU box with a downscale-stamped validated manifest, a roofline
# section (CPU peaks), the perf gate on its WARN path (rc=0), and ZERO
# cold compiles in the campaign trace (every compile event
# persistent-cache-served after the pre-pass). ~3 min, dominated by the
# pre-pass's one cold compile on a cold cache (seconds when warm).
CAMP_TMP=$(mktemp -d)
mkdir -p "$CAMP_TMP/archive"
cp benchmarks/*.json "$CAMP_TMP/archive/"
BENCH_FORCE_CPU=1 BENCH_BUDGET_S=130 BENCH_PROBE_S=1 \
  BENCH_ARCHIVE_DIR="$CAMP_TMP/warm" \
  BENCH_TRACE_PATH="$CAMP_TMP/warm_trace.jsonl" \
  python bench.py --group flight --out "$CAMP_TMP/warm_flight.json" \
  || { echo "campaign warm-cache pre-pass failed" >&2; rm -rf "$CAMP_TMP"; exit 1; }
BENCH_FORCE_CPU=1 BENCH_BUDGET_S=170 BENCH_PROBE_S=1 \
  BENCH_ARCHIVE_DIR="$CAMP_TMP/archive" \
  BENCH_JSON_PATH="$CAMP_TMP/BENCH.json" \
  BENCH_MULTICHIP_PATH="$CAMP_TMP/MULTICHIP.json" \
  BENCH_TREECODE_PATH="$CAMP_TMP/TREECODE.json" \
  BENCH_TRACE_PATH="$CAMP_TMP/trace.jsonl" \
  BENCH_PROFILE_ROOT="$CAMP_TMP/prof" \
  python bench.py --campaign --campaign-groups flight \
    > "$CAMP_TMP/line.json" \
  || { echo "campaign smoke failed" >&2; rm -rf "$CAMP_TMP"; exit 1; }
python - "$CAMP_TMP" <<'EOF'
import glob, json, sys

tmp = sys.argv[1]
line = json.load(open(tmp + "/line.json"))
camp = line.get("campaign") or {}
assert camp.get("gate_rc") == 0, f"downscaled campaign must WARN, not fail: {camp}"
manifest_path = sorted(glob.glob(tmp + "/archive/CAMPAIGN_r*.json"))[-1]
doc = json.load(open(manifest_path))
from skellysim_tpu.obs.perf import validate_campaign
errs = validate_campaign(doc)
assert not errs, errs
assert doc["downscaled"] is True, "CPU smoke must be downscale-stamped"
assert doc["groups"]["flight"]["status"] == "ok", doc["groups"]["flight"]
roof = doc["rooflines"].get("flight") or {}
assert roof.get("phases"), f"campaign must carry a roofline section: {roof}"
# zero cold compiles: after the warm-cache pre-pass every compile event
# in the campaign trace must be served from the persistent cache
compiles = [json.loads(ln) for ln in open(tmp + "/trace.jsonl")
            if '"compile"' in ln]
compiles = [e for e in compiles if e.get("ev") == "compile"]
cold = [e for e in compiles if not e.get("persistent_cache")]
assert not cold, f"{len(cold)}/{len(compiles)} COLD compiles in the campaign"
print(f"campaign smoke ok: manifest {manifest_path.rsplit('/', 1)[-1]} "
      f"valid, {roof.get('classified_frac')} classified, "
      f"{len(compiles)} cache-served compile(s), gate rc=0")
EOF
rm -rf "$CAMP_TMP"

echo "== obs: skelly-scope telemetry smoke (2-step run -> summarize + timeline) =="
# a real System.run with metrics+trace streams, rendered through the CLI:
# pins the acceptance path end to end (span events, compile events,
# convergence stats from one JSONL pair) in ~15 s, then merges the trace
# into a perfetto timeline and structurally validates it (>=1 host track
# with span slices — the `obs timeline` smoke)
OBS_TMP=$(mktemp -d)
JAX_PLATFORMS=cpu python -c "
from skellysim_tpu.utils.bootstrap import force_cpu_devices
force_cpu_devices(8)
import jax
jax.config.update('jax_enable_x64', True)
from skellysim_tpu.audit import fixtures
system = fixtures.make_system()
system.run(fixtures.free_state(system), max_steps=2,
           metrics_path='$OBS_TMP/metrics.jsonl',
           trace_path='$OBS_TMP/trace.jsonl')
"
python -m skellysim_tpu.obs summarize "$OBS_TMP"/metrics.jsonl "$OBS_TMP"/trace.jsonl \
  | grep -q "solver convergence" \
  || { echo "obs summarize smoke failed" >&2; rm -rf "$OBS_TMP"; exit 1; }
python -m skellysim_tpu.obs timeline "$OBS_TMP"/trace.jsonl -o "$OBS_TMP"/timeline.json \
  || { echo "obs timeline smoke failed" >&2; rm -rf "$OBS_TMP"; exit 1; }
python -c "
import json
doc = json.load(open('$OBS_TMP/timeline.json'))
evs = doc['traceEvents']
hosts = [e for e in evs if e.get('ph') == 'M' and e.get('name') == 'process_name']
assert hosts, 'timeline has no process tracks'
slices = [e for e in evs if e.get('ph') == 'X']
instants = [e for e in evs if e.get('ph') == 'i']
assert slices, 'timeline has no host span slices'
assert instants, 'timeline has no compile instants'
print(f'timeline smoke ok: {len(hosts)} track(s), {len(slices)} slice(s), '
      f'{len(instants)} instant(s)')
" || { echo "obs timeline validation failed" >&2; rm -rf "$OBS_TMP"; exit 1; }
rm -rf "$OBS_TMP"

echo "== bucket: warm-cache + zero-compile smoke (docs/performance.md) =="
# skelly-bucket acceptance, exit-code gated: (a) two CLI runs sharing one
# persistent --jax-cache — the second run must add ZERO new entries to the
# cache (every XLA compile served from disk) and stamp its compile events
# persistent_cache=true; (b) in-process, a second differently-shaped scene
# landing in an already-compiled capacity bucket must trigger ZERO new
# observed_jit traces. ~60 s, dominated by the first run's one cold compile.
BUCKET_TMP=$(mktemp -d)
JAX_PLATFORMS=cpu python - "$BUCKET_TMP" <<'EOF'
import json, os, subprocess, sys
import numpy as np

tmp = sys.argv[1]
cache = os.path.join(tmp, "jax_cache")

from skellysim_tpu.config import BackgroundSource, Config, Fiber

def write_cfg(path, shift):
    cfg = Config()
    cfg.params.dt_initial = cfg.params.dt_write = 0.005
    cfg.params.t_final = 0.01
    cfg.params.gmres_tol = 1e-10
    cfg.params.adaptive_timestep_flag = False
    for i in range(2):
        fib = Fiber(n_nodes=16, length=1.0, bending_rigidity=0.01)
        fib.fill_node_positions(np.array([shift + 2.0 * i, 0.0, 0.0]),
                                np.array([0.0, 0.0, 1.0]))
        cfg.fibers.append(fib)
    cfg.background = BackgroundSource(uniform=[1.0, 0.0, 0.0])
    cfg.save(path)

def cache_entries():
    if not os.path.isdir(cache):
        return set()
    return {f for f in os.listdir(cache) if not f.startswith(".")}

def run(tag):
    cfgdir = os.path.join(tmp, tag)
    os.makedirs(cfgdir)
    cfg = os.path.join(cfgdir, "cfg.toml")
    write_cfg(cfg, 0.0)
    trace = os.path.join(cfgdir, "trace.jsonl")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    subprocess.run([sys.executable, "-m", "skellysim_tpu",
                    "--config-file", cfg, "--jax-cache", cache,
                    "--trace-file", trace], env=env, check=True,
                   timeout=600)
    events = [json.loads(l) for l in open(trace)]
    return [e for e in events if e.get("ev") == "compile"]

c1 = run("run1")
entries1 = cache_entries()
assert entries1, "first run populated no persistent cache entries"
c2 = run("run2")
entries2 = cache_entries()
assert entries2 == entries1, (
    f"second run COMPILED fresh programs: {len(entries2 - entries1)} new "
    "persistent-cache entries (warm start must be fully cache-served)")
assert c2 and all(e.get("persistent_cache") for e in c2), (
    "second run's compile events are not stamped persistent_cache=true")
print(f"warm-cache smoke ok: run2 added 0/{len(entries1)} cache entries, "
      f"{len(c2)} cache-served compile event(s)")

# (b) in-process zero-compile bucket hit across differently-shaped scenes
from skellysim_tpu.utils.bootstrap import force_cpu_devices
force_cpu_devices(1)
import jax
jax.config.update("jax_enable_x64", True)
from skellysim_tpu.audit import fixtures
from skellysim_tpu.system import BackgroundFlow
from skellysim_tpu.system import buckets as bucket_mod

policy = bucket_mod.BucketPolicy(fiber_ladder=(8,), node_ladder=(32,))
system = fixtures.make_system()
for n_fib, n_nodes, seed in ((3, 16, 1), (5, 24, 2)):
    st = system.make_state(
        fibers=fixtures.make_fibers(n_fibers=n_fib, n_nodes=n_nodes,
                                    seed=seed),
        background=BackgroundFlow.make(uniform=(1.0, 0.0, 0.0)))
    st, key = bucket_mod.bucketize(st, policy)
    _, _, info = system.step(st)
    assert bool(info.converged)
assert system._solve_jit.trace_count == 1, (
    f"bucket hit retraced: {system._solve_jit.trace_count} traces")
print(f"bucket smoke ok: 2 scenes -> bucket {key.describe()}, 1 trace")
EOF
rm -rf "$BUCKET_TMP"

echo "== serve: skelly-serve smoke (2 tenants over TCP, docs/serving.md) =="
# the acceptance path end to end, in EVERY tier: boot the multi-tenant
# service as a real subprocess, admit two tenants over the wire, stream
# their trajectory frames, and gate the serving SLO — zero compile events
# after warmup (a warm-path retrace here is the serving-latency defect
# class the whole subsystem exists to prevent). ~45 s, dominated by the
# server's one warmup compile.
SERVE_TMP=$(mktemp -d)
JAX_PLATFORMS=cpu python - "$SERVE_TMP" <<'EOF'
import os, sys
import numpy as np
from skellysim_tpu.config import BackgroundSource, Config, Fiber, schema
from skellysim_tpu.config.toml_io import dumps
from skellysim_tpu.serve.client import SpawnedServer

def scene(shift):
    cfg = Config()
    cfg.params.dt_initial = cfg.params.dt_write = 0.005
    cfg.params.t_final = 0.02
    cfg.params.gmres_tol = 1e-10
    cfg.params.adaptive_timestep_flag = False
    fib = Fiber(n_nodes=8, length=1.0, bending_rigidity=0.01)
    fib.fill_node_positions(np.array([shift, 0.0, 0.0]),
                            np.array([0.0, 0.0, 1.0]))
    cfg.fibers = [fib]
    cfg.background = BackgroundSource(uniform=[1.0, 0.0, 0.0])
    return cfg

path = os.path.join(sys.argv[1], "serve_config.toml")
scene(0.0).save(path)
with open(path, "a") as fh:
    fh.write('\n[serve]\nmax_lanes = 2\nbatch_impl = "unroll"\n')

with SpawnedServer(path) as srv:
    with srv.client() as c:
        tids = [c.submit(dumps(schema.unpack(scene(s))))["tenant"]
                for s in (0.1, 0.3)]
        for tid in tids:
            st = c.wait(tid, timeout=180)
            assert st["status"] == "finished", st
            frames = c.stream(tid)["frames"]
            assert len(frames) >= 2, (tid, len(frames))
        stats = c.stats()
        assert stats["compiles_after_warm"] == 0, stats
    rc = srv.stop()
assert rc == 0, f"serve server exited rc={rc}"
print(f"serve smoke ok: 2 tenants finished, "
      f"{stats['frames_streamed_total']} frames streamed, "
      f"0 compiles after warm")
EOF
rm -rf "$SERVE_TMP"

echo "== scenarios: DI-ensemble smoke (docs/scenarios.md) =="
# skelly-scenario acceptance, exit-code gated in EVERY tier: a small
# CONFINED dynamic-instability sweep (periphery + nucleating body, B=2)
# runs on the ensemble vmap path with in-trace nucleation/catastrophe,
# at least one nucleation and one capacity-growth reseat, and ZERO
# warm-path compiles (compile events == capacity rungs). ~90 s, dominated
# by the two rung compiles (shared .jax_cache warms repeats).
JAX_PLATFORMS=cpu python -m skellysim_tpu.scenarios.smoke

echo "== guard: skelly-guard chaos smoke (docs/robustness.md) =="
# fault injection against the REAL service, in EVERY tier: NaN one
# tenant's lane -> status=failed with a verdict while its bucket sibling
# streams to completion; then SIGKILL the server mid-flight and restart
# it on the same write-ahead journal -> the live tenant is re-admitted
# and finishes. ~60 s (two server boots; the second reuses the first's
# .jax_cache so recovery pays recovery latency, not compile latency).
CHAOS_TMP=$(mktemp -d)
JAX_PLATFORMS=cpu python -m skellysim_tpu.guard.smoke "$CHAOS_TMP" \
  || { echo "guard chaos smoke failed" >&2; rm -rf "$CHAOS_TMP"; exit 1; }
rm -rf "$CHAOS_TMP"

echo "== spectral: periodic-scene smoke (docs/spectral.md) =="
# skelly-spectral acceptance, exit-code gated in EVERY tier: one implicit
# step on a triply-periodic box under pair_evaluator="spectral" — the
# plan builds off the rung ladder, the solve routes every flow through
# the particle-mesh evaluator, and GMRES must converge below gmres_tol.
# ~20 s (one compile; the periodic program shares no cache entry with the
# free-space smokes above).
JAX_PLATFORMS=cpu python -c "
from skellysim_tpu.utils.bootstrap import force_cpu_devices
force_cpu_devices(1)
import jax
jax.config.update('jax_enable_x64', True)
from skellysim_tpu.audit import fixtures
system = fixtures.make_system(pair_evaluator='spectral',
                              periodic_box=(12.0, 12.0, 12.0),
                              spectral_tol=1e-5)
state = fixtures.free_state(system)
_, _, info = system.step(state)
assert bool(info.converged), f'periodic spectral step did not converge: {info}'
res = float(info.residual)
assert res < system.params.gmres_tol, res
print(f'spectral smoke ok: periodic step converged, residual {res:.2e}')
"

echo "== docs: config reference in sync with the schema =="
JAX_PLATFORMS=cpu python scripts/gen_config_reference.py --check

echo "== unit/integration tests (tier: $TIER) =="
export XLA_FLAGS="--xla_force_host_platform_device_count=8"
# fast-tier budget guard: the not-slow tier must stay under the driver's
# 870 s timeout with headroom — above the warning line, slow-mark the
# newly-expensive tests (pytest.ini `slow`) instead of letting the tier
# creep into the timeout and fail far from the offending commit
TIER_BUDGET_WARN_S=780
TIER_LOG=$(mktemp)
trap 'rm -f "$TIER_LOG"' EXIT   # a red fast tier exits mid-case via set -e
tier_t0=$(date +%s)
case "$TIER" in
  # fast tier tees through a log and records per-test durations so a
  # budget trip below comes WITH the measurements the re-triage needs
  # (CHANGES.md PR 9 collected them by hand)
  fast)    JAX_PLATFORMS=cpu python -m pytest tests/ -q -m "not slow and not tpu" \
             --durations=25 --durations-min=1.0 2>&1 | tee "$TIER_LOG" ;;
  full)    JAX_PLATFORMS=cpu python -m pytest tests/ -q -m "not tpu" ;;
  nightly) python -m pytest tests/ -q ;;
  *) echo "unknown tier '$TIER' (use fast|full|nightly)" >&2; exit 2 ;;
esac
tier_wall=$(( $(date +%s) - tier_t0 ))
echo "== test tier wall: ${tier_wall}s =="
if [ "$TIER" = fast ] && [ "$tier_wall" -gt "$TIER_BUDGET_WARN_S" ]; then
  echo "!!!!!!!!!!!!!!!!!!!!!!!!!!!!!!!!!!!!!!!!!!!!!!!!!!!!!!!!!!!!!!!!!!" >&2
  echo "!! WARNING: not-slow tier took ${tier_wall}s (> ${TIER_BUDGET_WARN_S}s warning line," >&2
  echo "!! 870s hard timeout). Slow-mark the newly-expensive tests NOW —" >&2
  echo "!! see pytest.ini 'slow' and ROADMAP.md's tier-1 budget note."     >&2
  echo "!! Slowest tests this run (from pytest --durations=25):"           >&2
  sed -n '/slowest .* durations/,/^=\{10,\}/p' "$TIER_LOG" | sed 's/^/!!   /' >&2
  echo "!!!!!!!!!!!!!!!!!!!!!!!!!!!!!!!!!!!!!!!!!!!!!!!!!!!!!!!!!!!!!!!!!!" >&2
fi

echo "== graft entry: compile check + FULL-STEP multichip dryrun =="
# dryrun_multichip(8) is the full coupled implicit step as one explicitly-
# sharded shard_map program (parallel/spmd.py) on the 8-device virtual CPU
# mesh; it asserts residual AND solution parity against the 1-device solve
# to <= 5e-9 (the reference's backend-agreement gate) internally, plus the
# mixed-precision leg whose refinement sweeps run inside the mesh program.
JAX_PLATFORMS=cpu python -c "
import __graft_entry__ as ge
import jax
fn, args = ge.entry()
jax.jit(fn).lower(*args).compile()
print('entry() compiles')
ge.dryrun_multichip(8)
print('dryrun_multichip(8) full-step parity ok (gate %.0e)' % ge.PARITY_GATE)
"

echo "CI $TIER tier: PASS"
