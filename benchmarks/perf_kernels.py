"""Parametric kernel-timing harness (not asserted).

Counterpart of the reference's performance drivers
(`/root/reference/tests/core/performance_hydrodynamics_combined.cpp:36-150`):
times the pairwise Stokeslet/stresslet backends over log-spaced sizes and
prints a table of pair-throughput (src*trg pairs/sec). Backends:

  xla     - ops.kernels blocked dense kernels (any platform)
  pallas  - ops.pallas_kernels fused tiles (TPU; interpret elsewhere unless
            --allow-interpret, which is orders of magnitude slower)
  ring    - parallel.ring over all visible devices

Usage:
  python benchmarks/perf_kernels.py [--n-min 1024] [--n-max 65536]
      [--ntrials 3] [--kernel stokeslet|stresslet] [--backends xla,pallas]
"""

from __future__ import annotations

import argparse
import time

import numpy as np


def _time_call(fn, *args, ntrials=3, **kw):
    import jax

    out = fn(*args, **kw)
    jax.block_until_ready(out)  # compile + warm
    t0 = time.perf_counter()
    for _ in range(ntrials):
        out = fn(*args, **kw)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / ntrials


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--n-min", type=int, default=1024)
    ap.add_argument("--n-max", type=int, default=65536)
    ap.add_argument("--ntrials", type=int, default=3)
    ap.add_argument("--kernel", default="stokeslet",
                    choices=["stokeslet", "stresslet"])
    ap.add_argument("--backends", default="xla,pallas")
    ap.add_argument("--dtype", default="float32")
    ap.add_argument("--allow-interpret", action="store_true",
                    help="run the pallas backend in interpret mode off-TPU")
    args = ap.parse_args(argv)

    import jax
    import jax.numpy as jnp

    from skellysim_tpu.ops import kernels, pallas_kernels
    from skellysim_tpu import parallel

    backends = args.backends.split(",")
    platform = jax.default_backend()
    dtype = jnp.dtype(args.dtype)
    if "pallas" in backends and platform != "tpu" and not args.allow_interpret:
        print(f"# dropping pallas backend on platform={platform} "
              "(pass --allow-interpret to keep it, slowly)")
        backends = [b for b in backends if b != "pallas"]

    mesh = parallel.make_mesh() if "ring" in backends else None

    sizes = []
    n = args.n_min
    while n <= args.n_max:
        sizes.append(n)
        n *= 2

    rng = np.random.default_rng(0)
    print(f"# platform={platform} devices={jax.device_count()} "
          f"kernel={args.kernel} dtype={dtype.name} ntrials={args.ntrials}")
    print(f"{'n':>8} {'backend':>8} {'sec/eval':>12} {'pairs/sec':>14}")

    for n in sizes:
        r = jnp.asarray(rng.uniform(-5, 5, (n, 3)), dtype=dtype)
        if args.kernel == "stokeslet":
            f = jnp.asarray(rng.standard_normal((n, 3)), dtype=dtype)
            calls = {
                "xla": lambda: kernels.stokeslet_direct(r, r, f, 1.0),
                "pallas": lambda: pallas_kernels.stokeslet_pallas(
                    r, r, f, 1.0, interpret=(platform != "tpu")),
                "ring": (lambda: parallel.ring_stokeslet(r, r, f, 1.0,
                                                         mesh=mesh))
                if mesh and n % mesh.size == 0 else None,
            }
        else:
            S = jnp.asarray(rng.standard_normal((n, 3, 3)), dtype=dtype)
            calls = {
                "xla": lambda: kernels.stresslet_direct(r, r, S, 1.0),
                "pallas": lambda: pallas_kernels.stresslet_pallas(
                    r, r, S, 1.0, interpret=(platform != "tpu")),
                "ring": (lambda: parallel.ring_stresslet(r, r, S, 1.0,
                                                         mesh=mesh))
                if mesh and n % mesh.size == 0 else None,
            }
        for b in backends:
            call = calls.get(b)
            if call is None:
                continue
            dt = _time_call(lambda: call(), ntrials=args.ntrials)
            print(f"{n:>8} {b:>8} {dt:>12.3e} {n * n / dt:>14.3e}")


if __name__ == "__main__":
    main()
