#!/usr/bin/env python3
"""Heterogeneous simulation: mixed fiber resolutions + mixed body shapes.

The reference runs fibers of different node counts in one `std::list`
container and mixed body types in one `BodyContainer`
(`/root/reference/src/core/fiber_finite_difference.cpp:519-562`,
`body_container.cpp:523-550`). Here each resolution/shape becomes a dense
vmapped bucket (`SimState.fibers` / `.bodies` as tuples); the builder
buckets this config automatically and trajectory output stays in config
order. Short fibers resolve at 16 nodes, long ones at 64; a sphere and an
ellipsoid body coexist.

Usage:  python gen_config.py [skelly_config.toml]
then:   python -m skellysim_tpu.precompute skelly_config.toml
        python -m skellysim_tpu --config-file=skelly_config.toml
"""

import sys

import numpy as np

from skellysim_tpu.config import Body, Config, Fiber

config_file = sys.argv[1] if len(sys.argv) > 1 else "skelly_config.toml"
rng = np.random.default_rng(7)

config = Config()
config.params.eta = 1.0
config.params.dt_initial = 1e-2
config.params.dt_write = 0.1
config.params.t_final = 1.0

fibers = []
for i in range(8):                       # short, coarse fibers
    f = Fiber(length=0.5, bending_rigidity=2.5e-3, n_nodes=16)
    origin = rng.uniform(-3.0, 3.0, 3)
    normal = rng.normal(size=3)
    f.fill_node_positions(origin, normal / np.linalg.norm(normal))
    fibers.append(f)
for i in range(4):                       # long, fine fibers
    f = Fiber(length=2.0, bending_rigidity=1e-2, n_nodes=64)
    origin = rng.uniform(-3.0, 3.0, 3)
    normal = rng.normal(size=3)
    f.fill_node_positions(origin, normal / np.linalg.norm(normal))
    fibers.append(f)
config.fibers = fibers

config.bodies = [
    Body(position=[0.0, 0.0, -5.0], shape="sphere", radius=0.5,
         n_nodes=400, external_force=[0.0, 0.0, 0.5],
         precompute_file="sphere_body.npz"),
    Body(position=[0.0, 0.0, 5.0], shape="ellipsoid",
         axis_length=[0.8, 0.4, 0.4], n_nodes=600,
         external_force=[0.0, 0.0, -0.5],
         precompute_file="ellipsoid_body.npz"),
]

config.save(config_file)
print(f"wrote {config_file}: {len(config.fibers)} fibers "
      f"(16- and 64-node buckets), sphere + ellipsoid bodies")
