#!/usr/bin/env python3
"""Surface-of-revolution (oocyte) periphery + 3000 clamped fibers.

Counterpart of `/root/reference/examples/oocyte/gen_config.py`: the envelope
height function is revolved around x, fibers nucleate on the surface.
"""

import sys

import numpy as np

from skellysim_tpu.config import ConfigRevolution, Fiber

config_file = sys.argv[1] if len(sys.argv) > 1 else "skelly_config.toml"
rng = np.random.default_rng(100)

n_fibers = 3000

config = ConfigRevolution()
config.params.dt_write = 0.1
config.params.dt_initial = 1e-2
config.params.dt_max = 1e-2
config.params.periphery_interaction_flag = False
config.params.seed = 350
config.params.eta = 1.0

config.fibers = [
    Fiber(length=1.0, bending_rigidity=2.5e-3, force_scale=-0.05,
          minus_clamped=True, n_nodes=32)
    for _ in range(n_fibers)
]

config.periphery.envelope.n_nodes_target = 6000
config.periphery.envelope.lower_bound = -3.75
config.periphery.envelope.upper_bound = 3.75
config.periphery.envelope.height = \
    "0.5 * T * ((1 + 2*x/length)**p1) * ((1 - 2*x/length)**p2) * length"
config.periphery.envelope.T = 0.72
config.periphery.envelope.p1 = 0.4
config.periphery.envelope.p2 = 0.2
config.periphery.envelope.length = 7.5

config.periphery.move_fibers_to_surface(config.fibers, ds_min=0.1, rng=rng)

config.save(config_file)
print(f"wrote {config_file}; next: python -m skellysim_tpu.precompute")
