#!/usr/bin/env python3
"""10k free fibers in free space: the dense-Stokeslet scale-out config
(BASELINE.json #4, north-star: dense O(N^2) on a TPU mesh vs 32-rank FMM).

640k hydrodynamic nodes at 64 nodes/fiber. On a multi-chip mesh, run with
pair_evaluator = "ring" so source blocks rotate the ICI ring instead of
all-gathering (`skellysim_tpu/parallel/ring.py`).
"""

import sys

import numpy as np

from skellysim_tpu.config import Config, Fiber

config_file = sys.argv[1] if len(sys.argv) > 1 else "skelly_config.toml"
rng = np.random.default_rng(100)

n_fibers = 10_000
box = 20.0

config = Config()
config.params.dt_write = 0.05
config.params.dt_initial = 5e-3
config.params.dt_max = 5e-3
config.params.gmres_tol = 1e-8
config.params.pair_evaluator = "ring"

config.fibers = []
for _ in range(n_fibers):
    fib = Fiber(length=1.0, bending_rigidity=2.5e-3, force_scale=-0.05,
                n_nodes=64)
    origin = rng.uniform(-box / 2, box / 2, 3)
    direction = rng.normal(size=3)
    direction /= np.linalg.norm(direction)
    fib.fill_node_positions(origin, direction)
    config.fibers.append(fib)

config.save(config_file)
print(f"wrote {config_file} ({n_fibers} fibers); run: python -m skellysim_tpu")
