#!/usr/bin/env python3
"""10k free fibers in free space: the dense-Stokeslet scale-out config
(BASELINE.json #4, north-star: dense O(N^2) on a TPU mesh vs 32-rank FMM).

640k hydrodynamic nodes at 64 nodes/fiber. On a multi-chip mesh, run with
pair_evaluator = "ring" so source blocks rotate the ICI ring instead of
all-gathering (`skellysim_tpu/parallel/ring.py`).
"""

import sys

import numpy as np

from skellysim_tpu.config import Config, Fiber

config_file = sys.argv[1] if len(sys.argv) > 1 else "skelly_config.toml"
rng = np.random.default_rng(100)

n_fibers = 10_000
box = 20.0

config = Config()
config.params.dt_write = 0.05
config.params.dt_initial = 5e-3
config.params.dt_max = 5e-3
config.params.gmres_tol = 1e-8
config.params.pair_evaluator = "ring"
# f32 hot-loop flows through the fused Pallas VMEM tiles (single-chip AND
# each ring shard): 5.1 s/matvec at 640k nodes on one v5e vs ~28 s XLA.
# solver_precision="auto" keeps the hot loop f32 even under x64 (the
# pallas tier is f32-only; f64 operands would silently fall back to the
# exact tile). Alternative at scale: pair_evaluator = "ewald" (~1 s).
config.params.kernel_impl = "pallas"
config.params.solver_precision = "auto"

config.fibers = []
for _ in range(n_fibers):
    fib = Fiber(length=1.0, bending_rigidity=2.5e-3, force_scale=-0.05,
                n_nodes=64)
    origin = rng.uniform(-box / 2, box / 2, 3)
    direction = rng.normal(size=3)
    direction /= np.linalg.norm(direction)
    fib.fill_node_positions(origin, direction)
    config.fibers.append(fib)

config.save(config_file)
print(f"wrote {config_file} ({n_fibers} fibers); run: python -m skellysim_tpu")
