#!/usr/bin/env python3
"""Ellipsoidal periphery + 2000 surface-clamped fibers with motor forcing.

Counterpart of `/root/reference/examples/ellipsoid/gen_config.py`.
"""

import sys

import numpy as np

from skellysim_tpu.config import ConfigEllipsoidal, Fiber

config_file = sys.argv[1] if len(sys.argv) > 1 else "skelly_config.toml"
rng = np.random.default_rng(100)

n_fibers = 2000

config = ConfigEllipsoidal()
config.params.dt_write = 0.1
config.params.dt_initial = 8e-3
config.params.dt_max = 8e-3

config.fibers = [
    Fiber(length=1.0, bending_rigidity=2.5e-3, parent_body=-1,
          force_scale=-0.05, minus_clamped=True, n_nodes=64)
    for _ in range(n_fibers)
]

config.periphery.n_nodes = 8000
config.periphery.move_fibers_to_surface(config.fibers, ds_min=0.1, rng=rng)

config.save(config_file)
print(f"wrote {config_file}; next: python -m skellysim_tpu.precompute")
