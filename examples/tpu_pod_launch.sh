#!/bin/bash
# Multi-host launch examples for a TPU pod slice (or any multi-process run).
#
# Parity slot: the reference ships a SLURM submission example that mpiruns
# the binary across nodes (`/root/reference/examples/skelly_sim_slurm_sbatch.sh`);
# the TPU-native equivalent launches ONE PYTHON PROCESS PER HOST, and
# `jax.distributed` + GSPMD do what mpirun + MPI collectives did — ICI
# collectives within a slice, DCN across slices
# (`skellysim_tpu/parallel/multihost.py`).
#
# ----------------------------------------------------------------- Cloud TPU
# On a Cloud TPU pod slice, jax.distributed.initialize() autodiscovers the
# topology from the metadata server — run the SAME command on every host:
#
#   gcloud compute tpus tpu-vm ssh "$TPU_NAME" --worker=all --command='
#     cd ~/skellysim_tpu &&
#     python -m skellysim_tpu --config-file=skelly_config.toml'
#
# --------------------------------------------------------------------- SLURM
# On a SLURM cluster fronting TPU/accelerator hosts (the reference's cluster
# shape), submit with one task per host; the coordinator is task 0's host:
#
#   #SBATCH --nodes=4
#   #SBATCH --ntasks-per-node=1
#
#   head=$(scontrol show hostnames "$SLURM_JOB_NODELIST" | head -n1)
#   srun bash -c '
#     SKELLY_COORDINATOR='"$head"':8476 \
#     SKELLY_NUM_PROCS=$SLURM_NTASKS \
#     SKELLY_PROC_ID=$SLURM_PROCID \
#       python -m skellysim_tpu --config-file=skelly_config.toml'
#
# Every process writes nothing except process 0 (trajectory funnels there,
# like the reference's rank 0); resume is rank-count-INDEPENDENT (the RNG
# streams are not per-rank, unlike the reference's
# `trajectory_reader.cpp:204-219` restriction).
#
# ---------------------------------------------------------------- two-host smoke
# The in-repo smoke test of this path (two processes on one machine over
# loopback, CPU devices) is `tests/test_multihost.py` — the analogue of the
# reference's `mpiexec -n 2` ctest tier.
echo "This file is documentation — read the comments and adapt to your cluster."
