#!/usr/bin/env python3
"""Single 64-node fiber sedimenting under a uniform background flow
(BASELINE.json #2; `/root/reference/examples/stokes_tests/fiber_const_force`)."""

import sys

import numpy as np

from skellysim_tpu.config import BackgroundSource, Config, Fiber

config_file = sys.argv[1] if len(sys.argv) > 1 else "skelly_config.toml"

config = Config()
config.params.dt_initial = 0.01
config.params.dt_write = 0.01
config.params.t_final = 0.5
config.params.adaptive_timestep_flag = False

fib = Fiber(length=1.0, bending_rigidity=1e-2, n_nodes=64)
fib.fill_node_positions(np.zeros(3), np.array([0.0, 0.0, 1.0]))
config.fibers = [fib]
config.background = BackgroundSource(uniform=[0.1, 0.0, 0.0])

config.save(config_file)
print(f"wrote {config_file}; run: python -m skellysim_tpu")
