#!/usr/bin/env python3
"""Rigid sphere under constant force: the 6 pi eta R v Stokes-drag oracle
(`/root/reference/tests/combined/test_body_const_force.py` setup)."""

import sys

from skellysim_tpu.config import Body, Config

config_file = sys.argv[1] if len(sys.argv) > 1 else "skelly_config.toml"

config = Config()
config.params.eta = 1.0
config.params.dt_initial = 0.1
config.params.dt_write = 0.1
config.params.t_final = 3.0
config.params.adaptive_timestep_flag = False

config.bodies = [Body(position=[0.0, 0.0, 0.0], shape="sphere", radius=0.5,
                      n_nodes=600, external_force=[0.0, 0.0, 1.0])]

config.save(config_file)
print(f"wrote {config_file}; next: python -m skellysim_tpu.precompute")
