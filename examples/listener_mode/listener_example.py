#!/usr/bin/env python3
"""Drive the --listen post-processing server: streamlines, vortex lines, and a
velocity field slice from an existing trajectory
(`/root/reference/examples/listener_mode/listener_example.py`)."""

import numpy as np

from skellysim_tpu.io import Listener, Request, StreamlinesRequest, \
    VelocityFieldRequest

with Listener(toml_file="skelly_config.toml") as listener:
    req = Request(frame_no=0)

    # streamlines seeded on a small ring around the fiber
    theta = np.linspace(0, 2 * np.pi, 8, endpoint=False)
    req.streamlines = StreamlinesRequest(
        dt_init=0.05, t_final=0.5, back_integrate=True,
        x0=np.stack([0.3 * np.cos(theta), 0.3 * np.sin(theta),
                     0.5 * np.ones_like(theta)], axis=1))

    # velocity field on a coarse y=0 slice
    xs, zs = np.meshgrid(np.linspace(-1, 1, 11), np.linspace(-0.5, 1.5, 11))
    req.velocity_field = VelocityFieldRequest(
        x=np.stack([xs.ravel(), np.zeros(xs.size), zs.ravel()], axis=1))

    res = listener.request(req)

print(f"frame {res['i_frame']}/{res['n_frames']} at t={res['time']:.3f}")
for i, line in enumerate(res["streamlines"]):
    print(f"  streamline {i}: {line['x'].shape[0]} points, "
          f"t in [{line['time'][0]:.3f}, {line['time'][-1]:.3f}]")
vf = np.asarray(res["velocity_field"]).reshape(-1, 3)
print(f"  velocity field: {vf.shape[0]} points, "
      f"max |u| = {np.linalg.norm(vf, axis=1).max():.4f}")
