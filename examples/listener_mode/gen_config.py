#!/usr/bin/env python3
"""Small sim to drive with the listener-mode post-processing server
(`/root/reference/examples/listener_mode/gen_config.py`)."""

import sys

import numpy as np

from skellysim_tpu.config import BackgroundSource, Config, Fiber

config_file = sys.argv[1] if len(sys.argv) > 1 else "skelly_config.toml"

config = Config()
config.params.dt_initial = 0.01
config.params.dt_write = 0.02
config.params.t_final = 0.2
config.params.adaptive_timestep_flag = False

fib = Fiber(length=1.0, bending_rigidity=1e-2, n_nodes=32)
fib.fill_node_positions(np.zeros(3), np.array([0.0, 0.0, 1.0]))
config.fibers = [fib]
config.background = BackgroundSource(uniform=[0.5, 0.0, 0.0])

config.save(config_file)
print(f"wrote {config_file}; run the sim, then listener_example.py")
