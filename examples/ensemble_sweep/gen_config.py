#!/usr/bin/env python3
"""Ensemble sweep example: one advected fiber, rigidity x flow strength.

Generates the BASE run config plus an `ensemble.toml` sweep spec; then:

    python examples/ensemble_sweep/gen_config.py
    python -m skellysim_tpu.ensemble --sweep-file=ensemble.toml

streams 3 rigidities x 2 flow strengths = 6 members through 8 compiled
lanes (docs/ensemble.md), writing one reference-format trajectory per member
(`m00000.out`...) plus `ensemble_metrics.jsonl`. Both swept keys land in
member STATE (the one-compiled-program rule for sweeps). `replicas` stays 1:
the batched runner has no stochastic dynamics yet (dynamic instability is
host-side), so replicas of one sweep point would run identical physics.
"""

import sys

import numpy as np

from skellysim_tpu.config import BackgroundSource, Config, Fiber

config_file = sys.argv[1] if len(sys.argv) > 1 else "skelly_config.toml"

config = Config()
config.params.eta = 1.0
config.params.dt_initial = 0.01
config.params.dt_write = 0.05
config.params.t_final = 0.5
config.params.gmres_tol = 1e-10
config.params.seed = 100

fib = Fiber(n_nodes=32, length=1.0, bending_rigidity=0.0025)
fib.fill_node_positions(np.zeros(3), np.array([0.0, 0.0, 1.0]))
config.fibers = [fib]
config.background = BackgroundSource(uniform=[0.5, 0.0, 0.0])
config.save(config_file)

with open("ensemble.toml", "w") as fh:
    fh.write(f"""\
[ensemble]
base_config = "{config_file}"
replicas = 1
batch = 8

[[ensemble.sweep]]
key = "fibers.0.bending_rigidity"
values = [0.0025, 0.005, 0.01]

[[ensemble.sweep]]
key = "background.uniform.0"
values = [0.25, 0.5]
""")
print(f"wrote {config_file} + ensemble.toml")
