#!/usr/bin/env python3
"""Trajectory analysis walkthrough (`/root/reference/examples/analysis_example.py`):
read frames, extract fiber/body state, and evaluate the velocity field at
targets from a loaded frame."""

import sys

import numpy as np

from skellysim_tpu import builder
from skellysim_tpu.io.trajectory import TrajectoryReader, frame_to_state
from skellysim_tpu.system.system import solution_from_state

config_file = sys.argv[1] if len(sys.argv) > 1 else "skelly_config.toml"
traj_file = sys.argv[2] if len(sys.argv) > 2 else "skelly_sim.out"

reader = TrajectoryReader(traj_file)
print(f"{len(reader)} frames, t in [{reader.times[0]:.3f}, {reader.times[-1]:.3f}]")

frame = reader.load_frame(len(reader) - 1)
fibers = frame["fibers"][1]
bodies = [b for sub in frame["bodies"] for b in sub]
print(f"last frame: {len(fibers)} fibers, {len(bodies)} bodies")
if fibers:
    x0 = np.asarray(fibers[0]["x_"])
    print(f"fiber 0: {fibers[0]['n_nodes_']} nodes, "
          f"minus end at {x0[0]}, plus end at {x0[-1]}")

# velocity field from the solved state
system, template, _ = builder.build_simulation(config_file)
state = frame_to_state(frame, template)
solution = solution_from_state(state)
targets = np.array([[0.5, 0.0, 0.5], [1.0, 0.0, 0.5], [2.0, 0.0, 0.5]])
u = np.asarray(system.velocity_at_targets(state, solution, targets))
for r, v in zip(targets, u):
    print(f"u({r}) = {v}")
